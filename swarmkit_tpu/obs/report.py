"""Trace analysis: phase tables and schema validation.

Consumed three ways: ``scripts/trace_report.py`` (CLI), ``bench.py``
(embeds a per-config phase table in the BENCH artifact, derived from the
same trace JSON it writes), and the tier-1 smoke test (schema-validates
an emitted trace).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# span names making up the device-plan phase vs the host-commit phase —
# the pair whose overlap answers ROADMAP item 1's question ("is plan
# hidden behind commit?")
PLAN_PHASES = ("plan.dispatch", "plan.d2h", "plan.feasibility",
               # whole dispatch→fetch window of one plan (retro span):
               # captures compute hidden behind commits that the d2h
               # wait alone cannot see (ops/planner.py _note_inflight)
               "plan.inflight")
COMMIT_PHASES = ("sched.commit",)


def x_events(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [e for e in doc.get("traceEvents", ())
            if e.get("ph") == "X"]


def config_windows(doc: Dict[str, Any]
                   ) -> List[Tuple[str, Tuple[int, int]]]:
    """(cfg label, (ts_lo, ts_hi)) per ``bench.config`` marker span —
    the single definition both bench.py and scripts/trace_report.py use
    to attribute phases, so artifact tables and CLI reports can never
    disagree on the same trace file."""
    return [(e["args"].get("cfg", "?"), (e["ts"], e["ts"] + e["dur"]))
            for e in x_events(doc) if e["name"] == "bench.config"]


def _merge(intervals: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sorted, non-overlapping union of [start, end) us intervals.
    Overlap/union math runs on merged sets only — concurrent spans of
    the same phase (the pipelining PR will produce them) must not be
    double-counted."""
    merged: List[Tuple[int, int]] = []
    for s, e in sorted(intervals):
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return merged


def _union_seconds(merged: List[Tuple[int, int]]) -> float:
    """Total covered length of a MERGED interval set, in seconds."""
    return sum(e - s for s, e in merged) / 1e6


def _overlap_seconds(a: List[Tuple[int, int]],
                     b: List[Tuple[int, int]]) -> float:
    """Intersection length of two MERGED interval sets, in seconds."""
    i = j = 0
    total = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total / 1e6


def phase_table(doc: Dict[str, Any],
                window: Optional[Tuple[int, int]] = None
                ) -> Dict[str, Any]:
    """Summarize a Chrome trace into a per-phase table.

    ``window``: optional (ts_lo, ts_hi) in trace microseconds — restricts
    the table to spans starting inside it (bench uses the enclosing
    ``bench.config`` span to attribute phases per config).
    """
    phases: Dict[str, Dict[str, float]] = {}
    plan_iv: List[Tuple[int, int]] = []
    commit_iv: List[Tuple[int, int]] = []
    for e in x_events(doc):
        ts, dur = e["ts"], e["dur"]
        if window is not None and not (window[0] <= ts <= window[1]):
            continue
        row = phases.setdefault(
            e["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0})
        row["count"] += 1
        row["total_s"] += dur / 1e6
        row["max_s"] = max(row["max_s"], dur / 1e6)
        if e["name"] in PLAN_PHASES:
            plan_iv.append((ts, ts + dur))
        elif e["name"] in COMMIT_PHASES:
            commit_iv.append((ts, ts + dur))
    for row in phases.values():
        row["total_s"] = round(row["total_s"], 6)
        row["max_s"] = round(row["max_s"], 6)
    plan_iv = _merge(plan_iv)
    commit_iv = _merge(commit_iv)
    plan_s = _union_seconds(plan_iv)
    commit_s = _union_seconds(commit_iv)
    overlap = _overlap_seconds(plan_iv, commit_iv)
    return {
        "phases": dict(sorted(phases.items())),
        "plan_wall_s": round(plan_s, 6),
        "commit_wall_s": round(commit_s, 6),
        "plan_commit_overlap_s": round(overlap, 6),
        # fraction of device-plan wall time hidden behind host commit;
        # 0.0 today (sequential) — the pipelining PR moves this
        "plan_hidden_frac": round(overlap / plan_s, 4) if plan_s else 0.0,
        # the mirror fraction: host-commit wall time hidden behind the
        # device plan — the commit-plane headline ISSUE 13 tracks
        "commit_hidden_frac": round(overlap / commit_s, 4)
        if commit_s else 0.0,
    }


def format_table(table: Dict[str, Any]) -> str:
    lines = [f"{'phase':<28} {'count':>8} {'total_s':>12} {'max_s':>12}"]
    for name, row in table["phases"].items():
        lines.append(f"{name:<28} {row['count']:>8} "
                     f"{row['total_s']:>12.6f} {row['max_s']:>12.6f}")
    lines.append("")
    lines.append(f"plan wall   : {table['plan_wall_s']:.6f}s")
    lines.append(f"commit wall : {table['commit_wall_s']:.6f}s")
    lines.append(f"overlap     : {table['plan_commit_overlap_s']:.6f}s "
                 f"(plan hidden: {table['plan_hidden_frac'] * 100:.1f}%)")
    return "\n".join(lines)


def diff_phase_tables(a: Dict[str, Any], b: Dict[str, Any]
                      ) -> Dict[str, Any]:
    """Side-by-side diff of two ``phase_table`` results (A = baseline,
    B = candidate).  ``delta_pct`` is B vs A on total_s; None when A has
    no time in that phase.  Shared by ``scripts/trace_report.py --diff``
    and tests, so CLI output and assertions use one aggregation."""
    names = sorted(set(a.get("phases", {})) | set(b.get("phases", {})))
    rows = []
    for n in names:
        ra = a.get("phases", {}).get(n) or {"count": 0, "total_s": 0.0}
        rb = b.get("phases", {}).get(n) or {"count": 0, "total_s": 0.0}
        ta, tb = ra["total_s"], rb["total_s"]
        rows.append({
            "phase": n,
            "a_count": ra["count"], "b_count": rb["count"],
            "a_total_s": ta, "b_total_s": tb,
            "delta_pct": round((tb - ta) / ta * 100.0, 1) if ta else None,
        })
    summary = {}
    for key in ("plan_wall_s", "commit_wall_s", "plan_commit_overlap_s",
                "plan_hidden_frac"):
        summary[key] = (a.get(key, 0.0), b.get(key, 0.0))
    return {"rows": rows, "summary": summary}


def format_diff(diff: Dict[str, Any]) -> str:
    lines = [f"{'phase':<28} {'A cnt':>7} {'B cnt':>7} "
             f"{'A total_s':>12} {'B total_s':>12} {'delta':>8}"]
    for row in diff["rows"]:
        d = row["delta_pct"]
        if d is not None:
            delta = f"{d:+.1f}%"
        elif row["b_total_s"] and not row["a_total_s"]:
            delta = "new"
        else:
            delta = "="
        lines.append(
            f"{row['phase']:<28} {row['a_count']:>7} {row['b_count']:>7} "
            f"{row['a_total_s']:>12.6f} {row['b_total_s']:>12.6f} "
            f"{delta:>8}")
    lines.append("")
    for key, (va, vb) in diff["summary"].items():
        lines.append(f"{key:<22}: {va:.6f} -> {vb:.6f}")
    return "\n".join(lines)


def device_table(art: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Join a bench artifact's device-telemetry ledger with the device
    plane's occupancy window: kernel rows (bucket/route, dispatch vs
    D2H vs compile time) against what the plane as a whole reported.
    Returns None when the artifact carries no ``device_telemetry``
    (pre-PR-18 artifact, or telemetry off) — the CLI exits non-zero.
    Shared by ``scripts/trace_report.py --device`` and tests."""
    tel = art.get("device_telemetry")
    if not isinstance(tel, dict):
        return None
    plane = (art.get("planes") or {}).get("device") or {}
    rows = []
    for key, row in sorted((tel.get("kernel") or {}).items()):
        bucket, _, route = key.partition("|")
        rows.append({
            "bucket": bucket, "route": route,
            "dispatches": row.get("dispatches", 0),
            "groups": row.get("groups", 0),
            "task_rows": row.get("task_rows", 0),
            "node_rows": row.get("node_rows", 0),
            "dispatch_s": round(row.get("dispatch_ns", 0) / 1e9, 6),
            "d2h_s": round(row.get("d2h_ns", 0) / 1e9, 6),
            "compile_s": round(
                row.get("retro_compile_ns", 0) / 1e9, 6),
        })
    return {
        "device_plane": plane,
        "kernel": rows,
        "transfers": tel.get("transfers") or {},
        "bytes_avoided": tel.get("bytes_avoided", 0),
        "compile_cache": tel.get("compile_cache") or {},
        "memory": tel.get("memory") or {},
        "donation": tel.get("donation") or {},
    }


def format_device_table(table: Dict[str, Any]) -> str:
    plane = table["device_plane"]
    lines = [
        f"device plane: occupancy={plane.get('occupancy', 0.0)} "
        f"queue_depth={plane.get('queue_depth', 0.0)} "
        f"oldest_age_s={plane.get('oldest_age_s', 0.0)}",
        "",
        f"{'bucket':<40} {'route':<10} {'disp':>6} "
        f"{'dispatch_s':>11} {'d2h_s':>9} {'compile_s':>10}",
    ]
    for r in table["kernel"]:
        lines.append(
            f"{r['bucket']:<40} {r['route']:<10} {r['dispatches']:>6} "
            f"{r['dispatch_s']:>11.6f} {r['d2h_s']:>9.6f} "
            f"{r['compile_s']:>10.6f}")
    lines.append("")
    for direction in sorted(table["transfers"]):
        for reason, row in sorted(table["transfers"][direction].items()):
            lines.append(f"{direction} {reason:<16}: "
                         f"{row['bytes']:>14} B  x{row['count']}")
    lines.append(f"bytes avoided        : {table['bytes_avoided']:>14} B")
    cache = table["compile_cache"]
    misses = sum(r.get("misses", 0) for r in cache.values())
    hits = sum(r.get("hits", 0) for r in cache.values())
    lines.append(f"compile cache        : {len(cache)} signatures, "
                 f"{misses} misses, {hits} hits")
    don = table["donation"]
    if don:
        lines.append(
            f"donation balance     : donated={don.get('donated', 0)} "
            f"retired={don.get('retired', 0)} "
            f"outstanding={don.get('outstanding', 0)} "
            f"violations={don.get('violations', 0)}")
    return "\n".join(lines)


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema-validate a Chrome trace-event document.  Returns a list of
    problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    span_ids = set()
    parents = []
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph == "M":
            if e.get("name") != "thread_name":
                problems.append(f"event {i}: unknown metadata {e.get('name')}")
            continue
        if ph != "X":
            problems.append(f"event {i}: unsupported phase {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            problems.append(f"event {i}: missing name")
        for key in ("ts", "dur", "pid", "tid"):
            v = e.get(key)
            if not isinstance(v, int) or v < 0:
                problems.append(f"event {i}: bad {key}={v!r}")
        args = e.get("args")
        if not isinstance(args, dict) \
                or not isinstance(args.get("span_id"), int):
            problems.append(f"event {i}: args.span_id missing")
        else:
            span_ids.add(args["span_id"])
            if args.get("parent_id"):
                parents.append((i, args["parent_id"]))
    dropped = (doc.get("otherData") or {}).get("dropped_spans", 0)
    if not dropped:
        for i, pid in parents:
            if pid not in span_ids:
                problems.append(f"event {i}: parent {pid} not in trace")
    return problems
