"""Time-series metrics sampler feeding the flight recorder.

Periodically snapshots the metrics registry and records a compact delta
row into ``flightrec.samples``.  Two properties matter:

* **Time through the seam.**  Sample timestamps come from
  ``models.types.now()`` — under the simulator's VirtualClock a sample
  series is a pure function of the seed (the engine drives ``sample()``
  as an event; production runs ``start()``'s thread).

* **Deltas, not absolutes.**  The registry is process-global and
  long-lived; absolute counter values embed everything that ran before
  this capture.  ``rebase()`` pins a baseline and every sample records
  counters (and timer observation counts) relative to it, so two
  captures of the same workload produce identical rows.

Deterministic mode (the sim) drops everything wall-clock-tainted: timer
totals/quantiles are measured with ``perf_counter`` and gauges may be
written by wall-clock threads, so only counter and timer-count deltas —
pure event counts — are recorded.  Production mode keeps gauges and
timer totals for the health plane's benefit.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..models import types as _types
from ..utils.metrics import Registry
from ..utils.metrics import registry as _default_registry
from .flightrec import FlightRecorder, flightrec


class Sampler:
    def __init__(self, registry: Optional[Registry] = None,
                 recorder: Optional[FlightRecorder] = None,
                 deterministic: bool = False, prefix: str = "swarm_"):
        self.registry = registry or _default_registry
        self.recorder = recorder or flightrec
        self.deterministic = deterministic
        self.prefix = prefix
        self._base_counters: Dict[str, float] = {}
        self._base_timer_counts: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rebase()

    # -------------------------------------------------------------- sampling

    def rebase(self) -> None:
        """Pin the delta baseline to the registry's current state (call
        at capture start; ``reset()`` on the registry also warrants
        one)."""
        reg = self.registry
        self._base_counters = reg.counters_snapshot(self.prefix)
        self._base_timer_counts = {
            name: t.count
            for name, t in reg.timers_snapshot(self.prefix).items()}

    def sample(self) -> Dict[str, object]:
        """Record one row: cumulative deltas since ``rebase()``.  Always
        returns the row; recording respects the recorder's enable
        flag."""
        # close the plane occupancy windows on the same cadence as the
        # rows that carry them: the rolled gauges land in this sample
        from . import planes as _planes
        _planes.roll_all()
        reg = self.registry
        t = _types.now()
        counters = {
            k: v - self._base_counters.get(k, 0.0)
            for k, v in reg.counters_snapshot(self.prefix).items()}
        counters = {k: v for k, v in sorted(counters.items()) if v}
        gauges = {} if self.deterministic else dict(
            sorted(reg.gauges_snapshot(self.prefix).items()))
        timer_counts = {}
        timer_totals = {}
        for name, timer in sorted(reg.timers_snapshot(self.prefix)
                                  .items()):
            d = timer.count - self._base_timer_counts.get(name, 0)
            if d:
                timer_counts[name] = d
                if not self.deterministic:
                    timer_totals[name] = round(timer.total, 6)
        row: Dict[str, object] = {"t": t, "counters": counters,
                                  "timer_counts": timer_counts}
        if gauges:
            row["gauges"] = gauges
        if timer_totals:
            row["timer_totals"] = timer_totals
        self.recorder.record_sample(row)
        return row

    # --------------------------------------------------------------- running

    def start(self, interval: float = 2.0,
              on_sample: Optional[Callable[[], None]] = None) -> None:
        """Production mode: a daemon thread samples every ``interval``
        seconds, drains the recorder's store subscription, then runs
        ``on_sample`` (the Manager passes the health evaluator)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.recorder.poll_store()
                    self.sample()
                    if on_sample is not None:
                        on_sample()
                except Exception:
                    pass   # observability must never take the plane down

        self._thread = threading.Thread(target=loop, name="obs-sampler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
