"""Span-based tracing core.

Dapper-style explicit spans (start/end with parent links) recorded into a
bounded in-memory buffer and exported as Chrome trace-event JSON — the
format ``chrome://tracing`` and Perfetto load directly.

Design constraints, in priority order:

* **Near-zero cost when disabled.**  ``tracer.span(...)`` on a disabled
  tracer returns a shared no-op context manager: one attribute load and
  one call, no allocation.  The hot paths (scheduler tick, planner group
  loop) are instrumented at *phase* granularity — per tick / per group,
  never per task — so even enabled tracing stays within the ≤3% budget
  bench.py measures.

* **Time-source aware.**  Timestamps come from ``models.types.now()``,
  the same seam the deterministic simulator's VirtualClock installs
  into.  Under the sim, every span timestamp is virtual time and every
  span id comes from a monotonic counter — so a simulation trace is a
  pure function of its seed, byte for byte (asserted in
  tests/test_obs.py).

* **Thread-safe.**  Production components record spans from their own
  threads; the buffer append and id allocation are lock-protected, and
  parent links are tracked per-thread (a span's parent is the innermost
  open span *on the same thread*).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

from ..models import types as _types


class Span:
    __slots__ = ("name", "cat", "start", "end", "span_id", "parent_id",
                 "thread", "args")

    def __init__(self, name: str, cat: str, start: float, span_id: int,
                 parent_id: int, thread: str,
                 args: Optional[Dict[str, Any]]):
        self.name = name
        self.cat = cat
        self.start = start
        self.end = start
        self.span_id = span_id
        self.parent_id = parent_id   # 0 = root
        self.thread = thread
        self.args = args

    @property
    def duration(self) -> float:
        return self.end - self.start


class _Noop:
    """Shared do-nothing context manager returned while disabled."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _Noop()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_cat", "_args", "span")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> Span:
        self.span = self._tracer.start_span(self._name, self._cat,
                                            self._args)
        return self.span

    def __exit__(self, *exc) -> bool:
        self._tracer.end_span(self.span)
        return False


class Tracer:
    """Bounded span recorder with explicit start/end and parent links."""

    def __init__(self, clock=None, max_spans: int = 262_144):
        # None -> models.types.now (late-bound so a VirtualClock installed
        # later still governs this tracer)
        self._clock = clock
        self.enabled = False
        # optional tap: called with every ended span (even ones the
        # bounded buffer dropped) — the flight recorder's black box
        # installs itself here.  Process-wide, so save/restore_state
        # deliberately leaves it alone.
        self.sink = None
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        # spans started but not yet ended, by id — exported as
        # "incomplete" so a live /debug/trace snapshot taken mid-tick
        # still contains every referenced parent
        self._open: Dict[int, Span] = {}
        self._next_id = 1
        self._local = threading.local()
        self.epoch = 0.0
        self.dropped = 0

    # ------------------------------------------------------------- lifecycle

    def _now(self) -> float:
        return self._clock() if self._clock is not None else _types.now()

    def enable(self) -> None:
        if not self._spans:
            self.epoch = self._now()
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop all recorded spans and restart ids; the next span's clock
        reading becomes the new epoch (per-run isolation).  Spans still
        open on other threads when reset runs belong to the previous
        recording session — end_span drops them (pre-epoch start)."""
        with self._lock:
            self._spans = []
            self._open = {}
            self._next_id = 1
            self.dropped = 0
            self.epoch = self._now()
        self._local = threading.local()

    def save_state(self):
        """Capture the recording state (buffer, ids, epoch, enabled) so
        an embedded recording session — the sim runner resets the shared
        tracer around each scenario — can hand the caller's trace back
        via restore_state afterwards."""
        with self._lock:
            return (self._spans, self._open, self._next_id, self.epoch,
                    self.dropped, self.enabled)

    def restore_state(self, state) -> None:
        with self._lock:
            (self._spans, self._open, self._next_id, self.epoch,
             self.dropped, enabled) = state
        self._local = threading.local()
        self.enabled = enabled

    # ------------------------------------------------------------- recording

    def span(self, name: str, cat: str = "", **args):
        """Context manager recording one span; no-op when disabled.
        ``args`` land in the exported event's args dict — keep them
        deterministic (counts, names), never wall-clock readings."""
        if not self.enabled:
            return _NOOP
        return _SpanCtx(self, name, cat, args or None)

    def start_span(self, name: str, cat: str = "",
                   args: Optional[Dict[str, Any]] = None) -> Span:
        t = self._now()
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1].span_id if stack else 0
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            sp = Span(name, cat, t, sid, parent,
                      threading.current_thread().name, args)
            self._open[sid] = sp
        stack.append(sp)
        return sp

    def end_span(self, sp: Span) -> None:
        sp.end = self._now()
        stack = getattr(self._local, "stack", None)
        if stack and stack[-1] is sp:
            stack.pop()
        elif stack and sp in stack:        # mismatched exit order
            stack.remove(sp)
        with self._lock:
            self._open.pop(sp.span_id, None)
            if sp.start < self.epoch:
                # started before the last reset: a leftover of the
                # previous recording session — exporting it would yield
                # a negative timestamp
                self.dropped += 1
                return
            elif len(self._spans) < self.max_spans:
                self._spans.append(sp)
            else:
                self.dropped += 1
        sink = self.sink
        if sink is not None:
            sink(sp)

    def record_complete(self, name: str, cat: str = "",
                        duration: float = 0.0, **args) -> Optional[Span]:
        """Record an already-measured span ending *now* — for events the
        caller only recognizes after timing them (an XLA compile is
        detected by a jit-cache-size delta once the call returns).  The
        span parents under the innermost open span on this thread, so a
        retroactive ``plan.compile`` nests inside ``plan.dispatch``."""
        if not self.enabled:
            return None
        end = self._now()
        start = max(self.epoch, end - max(0.0, duration))
        stack = getattr(self._local, "stack", None)
        parent = stack[-1].span_id if stack else 0
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            sp = Span(name, cat, start, sid, parent,
                      threading.current_thread().name, args or None)
            sp.end = end
            if len(self._spans) < self.max_spans:
                self._spans.append(sp)
            else:
                self.dropped += 1
        sink = self.sink
        if sink is not None:
            sink(sp)
        return sp

    # --------------------------------------------------------------- export

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def to_chrome(self) -> Dict[str, Any]:
        """Chrome trace-event JSON object (``traceEvents`` array of "X"
        complete events plus thread-name metadata).  Deterministic: events
        appear in end order (the order spans were recorded), thread ids
        are assigned by first appearance, and timestamps are integer
        microseconds relative to the tracer epoch."""
        t_now = self._now()
        with self._lock:
            spans = list(self._spans)
            open_spans = sorted(self._open.values(),
                                key=lambda s: s.span_id)
        tids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        for sp in spans:
            tid = tids.setdefault(sp.thread, len(tids) + 1)
            ev: Dict[str, Any] = {
                "name": sp.name, "cat": sp.cat or "default", "ph": "X",
                "ts": int(round((sp.start - self.epoch) * 1e6)),
                # clamped: a backwards wall-clock step (NTP) mid-span
                # must not emit a negative duration the validator and
                # chrome://tracing both reject
                "dur": max(0, int(round((sp.end - sp.start) * 1e6))),
                "pid": 1, "tid": tid,
                "args": dict(sp.args or {},
                             span_id=sp.span_id, parent_id=sp.parent_id),
            }
            events.append(ev)
        for sp in open_spans:
            # a live snapshot mid-tick: export in-flight spans too, so
            # every parent_id in the document resolves
            if sp.start < self.epoch:
                continue
            tid = tids.setdefault(sp.thread, len(tids) + 1)
            try:
                # the owning thread may be mutating args concurrently
                # (e.g. the dispatcher filling in a count mid-span)
                args = dict(sp.args) if sp.args else {}
            except RuntimeError:
                args = {}
            args.update(span_id=sp.span_id, parent_id=sp.parent_id,
                        incomplete=True)
            events.append({
                "name": sp.name, "cat": sp.cat or "default", "ph": "X",
                "ts": int(round((sp.start - self.epoch) * 1e6)),
                "dur": max(0, int(round((t_now - sp.start) * 1e6))),
                "pid": 1, "tid": tid,
                "args": args,
            })
        meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                 "args": {"name": tname}}
                for tname, tid in sorted(tids.items(), key=lambda kv: kv[1])]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"dropped_spans": self.dropped}}

    def to_json(self) -> str:
        return json.dumps(self.to_chrome(), sort_keys=True,
                          separators=(",", ":"))

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


# the process-wide tracer every instrumented component records into
tracer = Tracer()

# the flight recorder taps every ended span (cheap: one attribute check
# while the recorder is disabled)
from .flightrec import flightrec as _flightrec  # noqa: E402  (cycle-free)

tracer.sink = _flightrec.record_span
