from .hashing import str_hash
from .kernel import (
    GroupInputs, NodeInputs, StrategyInputs, feasibility_and_capacity,
    plan_group, plan_group_jit, plan_strategy, plan_strategy_jit,
    seg_packfill, seg_waterfill, spread_score, strategy_score,
)
from .planner import TPUPlanner
