from .hashing import str_hash
from .kernel import (
    GroupInputs, NodeInputs, feasibility_and_capacity, plan_group,
    plan_group_jit, seg_waterfill,
)
from .planner import TPUPlanner
