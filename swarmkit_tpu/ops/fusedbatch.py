"""Fused many-service batch builder: one program per tick.

The per-service planner pays one densify + one XLA dispatch + one D2H
round-trip per (service, spec-version) group, so a tick of G services
costs G device round-trips and G Python column builds — the
``shape_cost_x`` scaling wall (ROADMAP direction 1).  This module packs
an ordered run of *fusable* groups into ONE padded, shape-bucketed
tasks×nodes program (``ops.kernel.plan_fused``): shared node columns are
densified once, per-group columns land in bucketed group slots, and the
groups' sequential semantics (group g sees groups 0..g-1 applied) ride
the program's scan carry instead of host round-trips.  Placements are
byte-identical to the per-group path by construction — the carry updates
are exactly the per-group apply, restricted to the signals the kernel
reads.

A run is split into CHUNKS (``SWARM_FUSED_CHUNK`` groups each, always
>= 2 chunks per run) so the pipelined scheduler can overlap chunk i+1's
device compute with chunk i's host apply/commit; the carry is threaded
chunk-to-chunk as device arrays and never fetched.

Fusability is stricter than device-ability: a group that densifies fine
per-group but carries signals the fused carry does not model (generic
resources, host-published ports, multi-level spread trees,
shutdown-marked stragglers) simply breaks the run and takes the
per-group path — identical placements, one extra round-trip.  Any
builder/bucket overflow degrades the same way: group-by-group, never a
failed tick.

Resource arithmetic is exact: the carry holds int64 nano-cpus/bytes and
the kernel's floor-divisions match the host densifier bit-for-bit, so
the fused program traces and dispatches under ``enable_x64`` (scoped —
the rest of the process stays in default 32-bit mode).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from jax.experimental import enable_x64

from ..models.objects import Task
from ..models.types import PublishMode, TaskState
from ..scheduler import constraint as constraint_mod
from ..scheduler import strategy as strategy_mod
from ..scheduler.filters import normalize_arch
from .hashing import str_hash
from .kernel import (
    FusedCarry, FusedGroups, FusedShared, FusedStrategy, K_CLAMP,
)

# static shape buckets to bound recompiles (shared with the per-group
# planner — ops/planner.py imports these so both paths use one ladder)
CC_BUCKETS = (1, 4, 16)      # constraint slots
P_BUCKETS = (1, 4)           # platform slots

SENTINEL = (-1, -1)  # never matches any real hash column value


def bucket(n: int, buckets) -> Optional[int]:
    for b in buckets:
        if n <= b:
            return b
    return None


def n_bucket(n: int) -> int:
    b = 1024
    while b < n:
        b *= 2
    return b


def l_bucket(n: int) -> int:
    for b in (1, 16, 256, 4096):
        if n <= b:
            return b
    return 1 << (n - 1).bit_length()


def pow2_bucket(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def split_hash(h: int) -> Tuple[int, int]:
    # two non-negative int32 halves (62 effective bits)
    return (h >> 31) & 0x7FFFFFFF, h & 0x7FFFFFFF


def x64():
    """The scoped-x64 guard every fused trace/dispatch/transfer runs
    under (int64 resource carry — see module docstring)."""
    return enable_x64()


def default_chunk_groups() -> int:
    """Groups per fused chunk (SWARM_FUSED_CHUNK, default 4)."""
    raw = os.environ.get("SWARM_FUSED_CHUNK", "")
    try:
        v = int(raw)
    except ValueError:
        return 4
    return v if v > 0 else 4


def chunk_sizes(g: int, chunk: int) -> List[int]:
    """Split ``g`` groups into chunk sizes.  A run always yields >= 2
    chunks (when it has >= 2 groups) so the pipelined tick has a chunk
    of commits to overlap the next chunk's device compute with."""
    chunk = max(1, chunk)
    if g <= 1:
        return [g] if g else []
    if g <= chunk:
        first = (g + 1) // 2
        return [first, g - first]
    out = []
    rest = g
    while rest > 0:
        take = min(chunk, rest)
        out.append(take)
        rest -= take
    return out


# ------------------------------------------------- shared column builders
#
# Single source for the host-side densification the per-group planner
# (ops/planner.py _build_device_inputs) and the fused builder both use —
# placement parity between the two paths is load-bearing, so the column
# semantics live in exactly one place.

def con_column_key(con) -> "Tuple[Optional[str], Optional[str]]":
    """(column_key, expected_value) for one constraint's hash column.
    Plain keys compare the raw node value against the raw expression;
    node.ip compiles through constraint.ip_column_spec (canonical
    address / containing-network-at-prefix values — the hash/prefix
    column).  (None, None) = the constraint can never match (malformed
    node.ip): callers encode an op-== row against the sentinel, which
    rejects every node regardless of the written operator — exactly
    the host ``_match_ip`` malformed behavior."""
    if con.key.lower() == "node.ip":   # exact: "node.iptables" is an
        #                                UNKNOWN key (host rejects all)
        spec = constraint_mod.ip_column_spec(con)
        if spec is None:
            return None, None
        return spec
    return con.key, con.exp


def fill_constraints(node_value: Callable, infos, n: int, constraints,
                     con_hash: np.ndarray, con_op: np.ndarray,
                     con_exp: np.ndarray) -> None:
    """Fill one group's constraint columns: ``con_hash`` [Cc, 2, nb]
    zeroed, ``con_op`` [Cc] pre-filled 2 (disabled), ``con_exp``
    [Cc, 2] zeroed."""
    for ci, con in enumerate(constraints):
        col_key, expected = con_column_key(con)
        if col_key is None:
            con_op[ci] = 0
            con_exp[ci] = SENTINEL
            continue
        values = [node_value(info, col_key) for info in infos]
        if any(v is None for v in values):
            # unknown key: node never matches, regardless of op
            con_op[ci] = 0
            con_exp[ci] = SENTINEL
            continue
        hi_lo = [split_hash(str_hash(v)) for v in values]
        arr = np.array(hi_lo, np.int64).T  # [2, n]
        con_hash[ci, :, :n] = arr
        con_op[ci] = con.operator
        con_exp[ci] = split_hash(str_hash(expected))


def fill_platforms(platforms, plat: np.ndarray) -> None:
    """Fill one group's platform rows (``plat`` [P, 4] pre-filled -1)."""
    for pi, p in enumerate(platforms):
        os_h = split_hash(str_hash(p.os)) if p.os else (0, 0)
        arch = normalize_arch(p.architecture)
        arch_h = (split_hash(str_hash(arch)) if arch else (0, 0))
        plat[pi] = (*os_h, *arch_h)


def node_platform_hashes(infos, nb: int) -> Tuple[np.ndarray, np.ndarray]:
    """Node platform.os / normalized-arch hash columns ([2, nb] each).
    Nodes without a description get the sentinel (PlatformFilter
    rejects them)."""
    os_hash = np.zeros((2, nb), np.int32)
    arch_hash = np.zeros((2, nb), np.int32)
    for i, info in enumerate(infos):
        desc = info.node.description
        if desc and desc.platform:
            os_hash[:, i] = split_hash(str_hash(desc.platform.os))
            arch_hash[:, i] = split_hash(
                str_hash(normalize_arch(desc.platform.architecture)))
        else:
            os_hash[:, i] = SENTINEL
            arch_hash[:, i] = SENTINEL
    return os_hash, arch_hash


def group_quota_blocked(sched, t: Task) -> bool:
    """The frozen admission verdict for ``t``'s scheduling group: True
    when the scheduler's tenant ledger blocked it this tick (the quota
    mask column must reject every node).  Schedulers without the quota
    plane (or with it disabled) never block."""
    ledger = getattr(sched, "quota", None)
    if ledger is None or not getattr(sched, "quota_enabled", False):
        return False
    return ledger.group_blocked(t)


def fused_strategies_ok(planner) -> bool:
    """Whether the planner's fused entry can serve non-spread strategy
    groups: the default kernel (plan_fused's in-scan strategy switch)
    or an injected fn that declares ``supports_strategies``
    (parallel.sharded.ShardedPlanFn).  Stubs without the flag keep the
    pre-strategy contract: non-spread groups break the run."""
    fn = getattr(planner, "_fused_fn", None)
    return fn is None or bool(getattr(fn, "supports_strategies", False))


def needs_plugins(t: Task) -> bool:
    from ..scheduler.filters import _references_volume_plugin
    c = t.spec.container
    if c is not None and any(_references_volume_plugin(m)
                             for m in c.mounts):
        return True
    return (t.spec.log_driver is not None
            and t.spec.log_driver.name not in ("", "none"))


def plugin_mask(t: Task, infos, nb: int) -> np.ndarray:
    """Plugin/volume-driver feasibility column for one group."""
    from ..scheduler.filters import PluginFilter
    extra_mask = np.ones(nb, bool)
    pf = PluginFilter()
    if pf.set_task(t):
        for i, info in enumerate(infos):
            extra_mask[i] = pf.check(info)
    return extra_mask


def flat_leaf(infos, nb: int, descriptor: str
              ) -> Tuple[np.ndarray, int]:
    """Flat (single-level) spread leaf ids keyed by the raw preference
    value, first-appearance order.  Returns (leaf [nb], value count)."""
    from ..scheduler.nodeset import _pref_value
    leaf = np.zeros(nb, np.int32)
    values: Dict[str, int] = {}
    for i, info in enumerate(infos):
        v = _pref_value(info, descriptor) or ""
        leaf[i] = values.setdefault(v, len(values))
    return leaf, max(len(values), 1)


# ----------------------------------------------------------- fusability

class GroupSpec:
    """One fusable group's parsed routing facts, captured at probe time
    and reused by the builder and the apply phase."""

    __slots__ = ("group", "t", "k", "constraints", "platforms",
                 "pref_descriptor", "wants_plugins", "cpu_d", "mem_d",
                 "maxrep", "slot", "quota_blocked", "sid", "sname",
                 "weights")

    def __init__(self, group: Dict[str, Task], t: Task, k: int,
                 constraints, platforms, pref_descriptor, wants_plugins,
                 cpu_d: int, mem_d: int, maxrep: int,
                 quota_blocked: bool = False, sid: int = 0,
                 sname: str = "", weights=None):
        self.group = group
        self.t = t
        self.k = k
        self.constraints = constraints
        self.platforms = platforms
        self.pref_descriptor = pref_descriptor
        self.wants_plugins = wants_plugins
        self.cpu_d = cpu_d
        self.mem_d = mem_d
        self.maxrep = maxrep
        self.slot = 0    # service slot, assigned at build time
        # frozen tenant-quota admission verdict (group_quota_blocked):
        # True builds an all-False quota mask row for this group
        self.quota_blocked = quota_blocked
        # strategy routing facts: sid 0 = spread; non-spread groups
        # ride the fused in-scan strategy switch (FusedStrategy)
        self.sid = sid
        self.sname = sname
        self.weights = weights   # i32[4] (weighted strategy) or None


def probe_group(planner, sched,
                group: Dict[str, Task]) -> Optional[GroupSpec]:
    """Fusability check for one group: everything ``dispatch_group``
    would device-plan MINUS the signals the fused carry does not model
    (generic resources, host-published ports, multi-level spread,
    shutdown-marked stragglers).  None = the group breaks the run and
    takes the per-group path."""
    t = next(iter(group.values()))
    if not planner._supported(t):
        return None
    sinfo = strategy_mod.resolve(strategy_mod.strategy_of(t))
    if sinfo is None:
        # unknown strategy name: the host path serves it through the
        # spread tree and counts the strategy fallback
        return None
    flat = sinfo.sid != strategy_mod.STRAT_SPREAD
    if flat and not fused_strategies_ok(planner):
        # an injected fused fn without the strategy switch (test stubs,
        # older mesh fns): non-spread groups break the run and ride the
        # per-group strategy kernel instead
        return None
    k = len(group)
    if k == 0 or k > K_CLAMP:
        return None
    placement = t.spec.placement
    # non-spread strategies own the scoring stage and ignore spread
    # preferences entirely (the per-group route plans them flat too)
    prefs = [] if flat else \
        [p for p in (placement.preferences if placement else [])
         if p.spread]
    if len(prefs) > 1:
        return None    # multi-level spread: per-group hier path
    res = t.spec.resources.reservations if t.spec.resources else None
    if res and res.generic:
        return None    # per-task claim bookkeeping: per-group path
    if t.endpoint and any(p.publish_mode == PublishMode.HOST
                          and p.published_port
                          for p in t.endpoint.ports):
        return None    # cross-group port claims: per-group path
    if any(tk.desired_state > TaskState.COMPLETE
           for tk in group.values()):
        return None    # batched mirror counting needs active totals
    constraints = []
    if placement and placement.constraints:
        try:
            constraints = constraint_mod.parse(placement.constraints)
        except constraint_mod.InvalidConstraint:
            constraints = []
    if bucket(len(constraints), CC_BUCKETS) is None:
        return None    # constraint-slot overflow: per-group -> host
    platforms = placement.platforms if placement else []
    if bucket(max(len(platforms), 1), P_BUCKETS) is None:
        return None
    return GroupSpec(
        group, t, k, constraints, platforms,
        prefs[0].spread.spread_descriptor if prefs else None,
        needs_plugins(t),
        int(res.nano_cpus) if res else 0,
        int(res.memory_bytes) if res else 0,
        placement.max_replicas if placement else 0,
        quota_blocked=group_quota_blocked(sched, t),
        sid=sinfo.sid, sname=sinfo.name,
        weights=(strategy_mod.weights_of(t)
                 if sinfo.uses_weights else None))


# ------------------------------------------------------------ run builder

class FusedChunk:
    """One dispatch unit of a fused run."""

    __slots__ = ("start", "count", "gb", "groups", "strat", "arrays",
                 "tasks", "t0")

    def __init__(self, start: int, count: int, gb: int,
                 groups: FusedGroups, tasks: int, strat=None):
        self.start = start
        self.count = count
        self.gb = gb
        self.groups = groups   # np-backed FusedGroups; dropped at dispatch
        self.strat = strat     # np-backed FusedStrategy or None (spread)
        self.arrays = None     # dispatched (x, fail_counts, spill) triple
        self.tasks = tasks
        self.t0 = 0.0


class FusedRun:
    """A dispatched fused batch: chunks, device carry, and everything
    the apply phase needs."""

    __slots__ = ("sched", "specs", "cols", "shared", "carry", "chunks",
                 "next_dispatch", "next_fetch", "last_fetch_end", "L",
                 "nb", "cc", "pb", "sb", "has_quota", "has_strat",
                 "aborted", "dispatch_dead", "applied")

    def __init__(self, sched, specs, cols, shared, carry, chunks,
                 L, nb, cc, pb, sb, has_quota=False, has_strat=False):
        self.sched = sched
        self.specs = specs
        self.cols = cols
        self.shared = shared
        self.carry = carry
        self.chunks = chunks
        self.next_dispatch = 0
        self.next_fetch = 0
        self.last_fetch_end = 0.0   # perf_counter of the last fetch
        self.L = L
        self.nb = nb
        self.cc = cc
        self.pb = pb
        self.sb = sb
        self.has_quota = has_quota
        self.has_strat = has_strat
        self.aborted = False
        self.dispatch_dead = False
        self.applied = 0

    @property
    def n_groups(self) -> int:
        return len(self.specs)

    def bucket_label(self, chunk: FusedChunk) -> str:
        """Stable jit-signature name for one fused chunk shape."""
        q = "_q1" if self.has_quota else ""
        m = "_mx1" if self.has_strat else ""
        return (f"fused_g{chunk.gb}_nb{self.nb}_cc{self.cc}"
                f"_p{self.pb}_L{self.L}_s{self.sb}{q}{m}")


def build_run(planner, sched, specs: List[GroupSpec]
              ) -> Optional[FusedRun]:
    """Densify an ordered run of fusable groups into one fused batch.

    Returns None when the cluster has no valid nodes or a shared bucket
    cannot hold the run — the caller falls back to the per-group path
    (same placements, amortization lost)."""
    t0 = specs[0].t
    cols = planner._densify(sched, t0)
    infos, n, nb, valid, ready, cpu, mem, total = cols
    if n == 0:
        return None
    # resident fast paths (ops/streaming.py): per-service base counts,
    # platform hashes, failure rows and flat leaves come from the
    # planner's row-wise-maintained resident caches when these ARE the
    # resident columns (identity-guarded); the loops below remain the
    # tracker-less path and the differential oracle
    st = planner._resident_for(cols) \
        if hasattr(planner, "_resident_for") else None

    # ---- shared buckets across the run
    cc = max(bucket(len(sp.constraints), CC_BUCKETS) for sp in specs)
    pb = max(bucket(max(len(sp.platforms), 1), P_BUCKETS)
             for sp in specs)

    # ---- service slots (groups of one service share a slot so the
    # carry's per-service accumulator levels them together)
    slot_map: Dict[str, int] = {}
    for sp in specs:
        sp.slot = slot_map.setdefault(sp.t.service_id, len(slot_map))
    sb = pow2_bucket(len(slot_map))

    svc0 = np.zeros((sb, nb), np.int32)
    if st is not None:
        for sid, s in slot_map.items():
            svc0[s] = st.svc_tasks_col(sched, sid)
    else:
        for i, info in enumerate(infos):
            by_svc = info.active_tasks_count_by_service
            if not by_svc:
                continue
            for sid, c in by_svc.items():
                s = slot_map.get(sid)
                if s is not None and c:
                    svc0[s, i] = c

    if any(sp.platforms for sp in specs):
        if st is not None:
            os_hash, arch_hash = st.platform_hashes()
        else:
            os_hash, arch_hash = node_platform_hashes(infos, nb)
    else:
        os_hash = np.zeros((2, nb), np.int32)
        arch_hash = np.zeros((2, nb), np.int32)

    # ---- spread leaves (flat; multi-level trees never fuse) + shared L
    ts = planner.fail_ts()   # tick-frozen: parity with the per-group path
    fail_idx = list(st.fail_rows) if st is not None else \
        [i for i, info in enumerate(infos) if info.recent_failures]
    leaves: List[Optional[np.ndarray]] = []
    L = 1
    for sp in specs:
        if sp.pref_descriptor is not None:
            if st is not None:
                leaf, n_values = st.flat_leaf(sched, sp.pref_descriptor)
            else:
                leaf, n_values = flat_leaf(infos, nb, sp.pref_descriptor)
            leaves.append(leaf)
            L = max(L, l_bucket(n_values))
        else:
            leaves.append(None)

    shared = FusedShared(valid=valid, ready=ready, os_hash=os_hash,
                         arch_hash=arch_hash, svc0=svc0)
    # carry snapshot: int64 resource columns (exact math on device),
    # int32 totals; svc placements accumulate from zero within the run
    carry = FusedCarry(
        total=total.copy(), cpu=cpu.copy(), mem=mem.copy(),
        svc_acc=np.zeros((sb, nb), np.int32))

    # ---- chunk assembly.  Quota mask rows are built for the WHOLE run
    # when ANY group in it is quota-blocked (one shape per run); a run
    # with no blocked group ships quota_ok=None — the quota-free jit
    # signature, untouched.
    has_quota = any(sp.quota_blocked for sp in specs)
    # Strategy-mixed runs carry per-group strategy ids + weighted terms
    # and ONE run-wide learned-scorer parameter set (all groups share the
    # deployed scorer).  Spread-only runs ship strat=None — the
    # strategy-free jit signature, untouched.
    has_strat = any(sp.sid for sp in specs)
    if has_strat:
        if any(sp.sid == strategy_mod.STRAT_LEARNED for sp in specs):
            lw1, lb1, lw2, lb2 = strategy_mod.learned_params()
            lw1 = np.asarray(lw1, np.int32)
            lb1 = np.asarray(lb1, np.int32)
            lw2 = np.asarray(lw2, np.int32)
            lb2 = np.asarray(lb2, np.int32)
        else:
            f = len(strategy_mod.MLP_FEATURES)
            lw1 = np.zeros((f, 1), np.int32)
            lb1 = np.zeros(1, np.int32)
            lw2 = np.zeros(1, np.int32)
            lb2 = np.zeros((), np.int32)
    chunks: List[FusedChunk] = []
    start = 0
    for count in chunk_sizes(len(specs), default_chunk_groups()):
        gb = pow2_bucket(count)
        k = np.zeros(gb, np.int32)
        slot = np.zeros(gb, np.int32)
        maxrep = np.zeros(gb, np.int32)
        cpu_d = np.zeros(gb, np.int64)
        mem_d = np.zeros(gb, np.int64)
        con_hash = np.zeros((gb, cc, 2, nb), np.int32)
        con_op = np.full((gb, cc), 2, np.int32)
        con_exp = np.zeros((gb, cc, 2), np.int32)
        plat = np.full((gb, pb, 4), -1, np.int32)
        failures = np.zeros((gb, nb), np.int32)
        leaf = np.zeros((gb, nb), np.int32)
        extra = np.ones((gb, nb), bool)
        quota = np.ones((gb, nb), bool) if has_quota else None
        sid = np.zeros(gb, np.int32) if has_strat else None
        weights = np.zeros((gb, 4), np.int32) if has_strat else None
        tasks = 0
        for j in range(count):
            sp = specs[start + j]
            if quota is not None and sp.quota_blocked:
                quota[j] = False
            if sid is not None:
                sid[j] = sp.sid
                if sp.weights is not None:
                    weights[j] = sp.weights
            k[j] = sp.k
            slot[j] = sp.slot
            maxrep[j] = sp.maxrep
            cpu_d[j] = sp.cpu_d
            mem_d[j] = sp.mem_d
            tasks += sp.k
            if sp.constraints:
                if st is not None:
                    st.fill_constraints(sched, sp.constraints,
                                        con_hash[j], con_op[j],
                                        con_exp[j])
                else:
                    fill_constraints(planner._node_value, infos, n,
                                     sp.constraints, con_hash[j],
                                     con_op[j], con_exp[j])
            if sp.platforms:
                fill_platforms(sp.platforms, plat[j])
            for i in fail_idx:
                failures[j, i] = infos[i].count_recent_failures(ts, sp.t)
            if leaves[start + j] is not None:
                leaf[j] = leaves[start + j]
            if sp.wants_plugins:
                extra[j] = plugin_mask(sp.t, infos, nb)
        chunks.append(FusedChunk(
            start, count, gb,
            FusedGroups(k=k, slot=slot, maxrep=maxrep, cpu_d=cpu_d,
                        mem_d=mem_d, con_hash=con_hash, con_op=con_op,
                        con_exp=con_exp, plat=plat, failures=failures,
                        leaf=leaf, extra_mask=extra, quota_ok=quota),
            tasks,
            strat=(FusedStrategy(sid=sid, weights=weights, w1=lw1,
                                 b1=lb1, w2=lw2, b2=lb2)
                   if has_strat else None)))
        start += count

    return FusedRun(sched, specs, cols, shared, carry, chunks,
                    L, nb, cc, pb, sb, has_quota=has_quota,
                    has_strat=has_strat)
