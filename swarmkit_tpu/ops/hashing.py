"""Stable string hashing for on-device label/constraint matching.

Constraint matching is case-insensitive full-string equality (reference:
manager/constraint/constraint.go:85-counterpart), so strings can be replaced
by stable 63-bit hashes: equality of hashes == equality of strings up to a
2^-63 collision probability per pair.  Python's builtin hash() is salted per
process, so we use blake2b.
"""

from __future__ import annotations

import hashlib

# hash of the empty string is special-cased to 0 so "label absent" and
# "label == ''" coincide, matching reference semantics where a missing
# label behaves as the empty string.
EMPTY = 0


def str_hash(s: str) -> int:
    """Stable 63-bit hash of a string, case-insensitive. '' -> 0."""
    if s == "":
        return EMPTY
    digest = hashlib.blake2b(s.lower().encode(), digest_size=8).digest()
    value = int.from_bytes(digest, "little") & 0x7FFF_FFFF_FFFF_FFFF
    return value or 1  # avoid colliding a real string with EMPTY
