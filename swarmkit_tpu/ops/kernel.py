"""The TPU scheduling kernel: batched group placement as array programs.

This is the device-side replacement for the reference's hot loops
(manager/scheduler/scheduler.go:694 scheduleTaskGroup, :772
scheduleNTasksOnSubtree, :844 scheduleNTasksOnNodes, nodeset.go:50 tree):

* The filter pipeline (Ready/Resource/Constraint/Platform/Plugin/HostPort/
  MaxReplicas — filter.go) becomes a fused boolean feasibility mask over all
  nodes at once.
* The spread comparator (scheduler.go:708 nodeLess) becomes an integer
  "effective level" per node: per-service task count, down-weighted by
  recent failures.
* The sorted round-robin placement loop becomes **hierarchical
  water-filling**: raise a per-branch water level λ until the group's k
  tasks fit (respecting per-node capacity), then break ties among marginal
  nodes with a threshold search on (total-tasks, node-index).  This
  reproduces the reference's "level per-service counts first, then total
  counts, capacity-bounded" semantics without any sequential loop.

Everything is fixed-shape, fixed-iteration-count (binary searches with a
static iteration budget), 32-bit, and built exclusively from ops that XLA
maps well to TPU (segment-sums, elementwise selects).  The identical code
runs under plain `jit` (single chip) and under `shard_map` with the node
axis sharded over a mesh — the only difference is the `reduce` callback,
which becomes a `psum` over the node-axis (see parallel/sharded.py).

Numeric ranges (32-bit budget):
  per-service counts clamped to 2^20; failure down-weight factor 2^22
  (dominates any real count); water-level search over [0, 2^30); node index
  packed in 20 bits -> supports up to 2^20 (~1M) nodes per shard; group size
  k clamped to 2^22 (the planner falls back to the host path above that).

Resource accounting is **exact**: the host densifier compares int64
nano-cpus/bytes and floor-divides in int64 (matching the reference's integer
comparisons, api/types.proto:68), shipping the kernel a boolean ``res_ok``
mask and an int32 per-node capacity ``res_cap`` — no float rounding can
admit/reject a node the host oracle would decide differently.

Segment sums that can exceed int32 (fill volumes up to N*k ~ 2^42) are
computed in float32, which is safe *for comparisons against k <= K_CLAMP*:
all addends are non-negative, so every partial sum <= the true total; totals
< 2^24 are therefore exact at every step, and totals >= 2^24 keep enough
relative accuracy (error ~ N*eps) to stay far above K_CLAMP = 2^22 — either
way the `sum >= k` comparison is decided correctly.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..scheduler.nodeinfo import MAX_FAILURES  # single source of truth
from ..scheduler.strategy import (  # strategy-seam shared envelope
    BP_CLAMP, FEAT_CLAMP, HR_CLAMP, MLP_SHIFT, SCORE_CLAMP,
    STRAT_BINPACK, STRAT_LEARNED, STRAT_SPREAD, STRAT_WEIGHTED,
)

F_BIG = 1 << 22          # failure down-weight step (dominates svc counts)
FAILURE_CLAMP = 63       # keeps e = svc + failures*F_BIG inside int32
SVC_CLAMP = (1 << 20) - 1
K_CLAMP = 1 << 22        # max group size the kernel accepts (see docstring)
LOAD_CLAMP = (1 << 24) - 1   # branch-load clamp: the f32 segment sums are
                             # exact below 2^24, so clamping there keeps
                             # stage-A branch ordering exact; branches with
                             # >16.7M tasks of one service are equi-preferred
LEVEL_ITERS = 34         # binary search over [0, 2^30]; extra margin
TIE_ITERS = 34           # binary search over packed 31-bit tie keys
IDX_BITS = 20
TOTAL_CLAMP = (1 << 10) - 1   # total-tasks clamp: tie keys stay < 2^30 so
                              # the threshold search range fits in int32

Reduce = Callable[[jnp.ndarray], jnp.ndarray]


def _identity(x: jnp.ndarray) -> jnp.ndarray:
    return x


class GroupInputs(NamedTuple):
    """Per-(service, spec-version) task-group inputs, densified host-side."""

    k: jnp.ndarray              # i32 scalar: number of tasks to place
    con_hash: jnp.ndarray       # i32[Cc, 2, N]: node hash (hi,lo) per constraint
    con_op: jnp.ndarray         # i32[Cc]: 0 ==, 1 !=, 2 disabled
    con_exp: jnp.ndarray        # i32[Cc, 2]: expected (hi,lo)
    plat: jnp.ndarray           # i32[P, 4]: (os_hi, os_lo, arch_hi, arch_lo);
                                #   row -1 sentinel in col 0 = unused
    maxrep: jnp.ndarray         # i32 scalar: max replicas per node (0 = off)
    port_limited: jnp.ndarray   # bool scalar: group publishes host ports


class NodeInputs(NamedTuple):
    """Cluster-wide node state (SoA), maintained incrementally host-side."""

    valid: jnp.ndarray          # bool[N] (padding mask)
    ready: jnp.ndarray          # bool[N] READY && ACTIVE
    res_ok: jnp.ndarray         # bool[N] node meets this group's reservations
                                #   (exact int64 comparison, host-side)
    res_cap: jnp.ndarray        # i32[N] tasks of this group the node's
                                #   resources can absorb (exact int64 floor
                                #   division host-side, clipped to K_CLAMP)
    svc_tasks: jnp.ndarray      # i32[N] active tasks of this service
    total_tasks: jnp.ndarray    # i32[N] active tasks total
    failures: jnp.ndarray       # i32[N] recent failures for this service
    leaf: jnp.ndarray           # i32[N] spread-preference leaf id (0 if none)
    os_hash: jnp.ndarray        # i32[2, N] node platform.os hash (hi, lo)
    arch_hash: jnp.ndarray      # i32[2, N] normalized arch hash (hi, lo)
    port_conflict: jnp.ndarray  # bool[N] a requested host port is taken
    extra_mask: jnp.ndarray     # bool[N] plugin/volume masks ANDed host-side
    # tenant-quota mask column (scheduler/quota.py): all-False when the
    # group's tenant was exhausted at admission.  None (the default)
    # keeps the quota-free jit signatures unchanged — the column is
    # only materialized for blocked groups.
    quota_ok: Optional[jnp.ndarray] = None   # bool[N] or None


def _seg_sum_f32(x: jnp.ndarray, seg: jnp.ndarray, L: int) -> jnp.ndarray:
    """int32 segment sum carried in f32 so totals up to N*k (~2^42) cannot
    wrap.  Safe for comparisons against bounds <= K_CLAMP — see module
    docstring for the exactness argument."""
    return jax.ops.segment_sum(x.astype(jnp.float32), seg, num_segments=L)


def seg_waterfill(e: jnp.ndarray, cap: jnp.ndarray, tie: jnp.ndarray,
                  k_seg: jnp.ndarray, seg: jnp.ndarray, L: int,
                  reduce: Reduce = _identity) -> jnp.ndarray:
    """Capacity-bounded water-filling within each segment.

    Finds per-segment level λ, assigns x_i = clip(λ-1 - e_i, 0, cap_i), then
    grants the remainder one-by-one to marginal nodes in ``tie`` order.

    e:    i32[N] current level per element (lower = preferred)
    cap:  i32[N] max units this element can take
    tie:  i32[N] tie-break key, unique per element (lower = preferred)
    k_seg:i32[L] units to place per segment (each <= K_CLAMP)
    seg:  i32[N] segment id per element
    reduce: cross-shard sum for [L]-shaped partials (psum under shard_map)
    """
    e = e.astype(jnp.int32)
    cap = cap.astype(jnp.int32)
    kf = k_seg.astype(jnp.float32)

    def fill_at(lam_seg: jnp.ndarray) -> jnp.ndarray:
        return jnp.clip(lam_seg[seg] - e, 0, cap)

    def level_body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo) // 2   # avoids int32 overflow of lo + hi
        f = reduce(_seg_sum_f32(fill_at(mid), seg, L))
        ge = f >= kf
        return jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi)

    lo = jnp.zeros((L,), jnp.int32)
    hi = jnp.full((L,), 1 << 30, jnp.int32)
    lo, hi = jax.lax.fori_loop(0, LEVEL_ITERS, level_body, (lo, hi))
    lam = hi  # minimal λ with fill ≥ k (or 2^30 if capacity-infeasible)

    x_base = fill_at(lam - 1)
    f_base = reduce(_seg_sum_f32(x_base, seg, L))
    # remainder is exact: whenever r > 0, f_base < k <= K_CLAMP < 2^24
    r = jnp.maximum(kf - f_base, 0.0)

    marginal = (e <= lam[seg] - 1) & (x_base < cap)

    # threshold search: per segment, the r-th smallest tie key among marginals
    def tie_body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo) // 2   # avoids int32 overflow of lo + hi
        cnt = reduce(_seg_sum_f32(
            (marginal & (tie <= mid[seg])).astype(jnp.int32), seg, L))
        ge = cnt >= r
        return jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi)

    tlo = jnp.full((L,), -1, jnp.int32)
    thi = jnp.full((L,), 1 << 30, jnp.int32)  # tie keys are < 2^30
    tlo, thi = jax.lax.fori_loop(0, TIE_ITERS, tie_body, (tlo, thi))
    grant = marginal & (tie <= thi[seg]) & (r[seg] > 0)

    return x_base + grant.astype(jnp.int32)


def _hash_eq(node_hash: jnp.ndarray, exp: jnp.ndarray) -> jnp.ndarray:
    """node_hash: i32[2, N], exp: i32[2] -> bool[N]."""
    return (node_hash[0] == exp[0]) & (node_hash[1] == exp[1])


def feasibility_and_capacity(nodes: NodeInputs, group: GroupInputs,
                             reduce: Reduce = _identity):
    """Fused filter pipeline: mask[N], per-node capacity[N], and per-filter
    failure counts (for user-visible ``no suitable node (...)`` diagnostics,
    matching pipeline.go's short-circuit failure accounting).

    Mirrors filter.go's checklist; a False anywhere is a node the host
    pipeline would also reject (modulo documented waivers).
    """
    # --- individual filter masks
    ready_m = nodes.ready
    res_m = nodes.res_ok       # exact int64 comparison done host-side
    plugin_m = nodes.extra_mask

    def apply_constraint(i, m):
        eq = _hash_eq(group.con_hash[i], group.con_exp[i])
        op = group.con_op[i]
        ok = jnp.where(op == 0, eq, jnp.where(op == 1, ~eq, True))
        return m & ok

    con_m = jax.lax.fori_loop(0, group.con_op.shape[0], apply_constraint,
                              jnp.ones_like(ready_m))

    def apply_platform(i, acc):
        row = group.plat[i]
        used = row[0] != -1
        os_ok = ((row[0] == 0) & (row[1] == 0)) | (
            (nodes.os_hash[0] == row[0]) & (nodes.os_hash[1] == row[1]))
        arch_ok = ((row[2] == 0) & (row[3] == 0)) | (
            (nodes.arch_hash[0] == row[2]) & (nodes.arch_hash[1] == row[3]))
        matched, any_used = acc
        return matched | (used & os_ok & arch_ok), any_used | used

    matched, any_used = jax.lax.fori_loop(
        0, group.plat.shape[0], apply_platform,
        (jnp.zeros_like(ready_m), jnp.zeros((), jnp.bool_)))
    plat_m = matched | ~any_used

    port_m = ~(group.port_limited & nodes.port_conflict)
    rep_m = (group.maxrep == 0) | (nodes.svc_tasks < group.maxrep)
    # tenant-quota mask column: last in the checklist, mirroring the
    # host pipeline's QuotaFilter position so short-circuit failure
    # counts (and therefore explanations) agree between the paths
    quota_m = nodes.quota_ok if nodes.quota_ok is not None \
        else jnp.ones_like(ready_m)

    # --- short-circuit failure counts in pipeline order (pipeline.go:10-20)
    prior = nodes.valid
    fail_counts = []
    mask = prior
    for m in (ready_m, res_m, plugin_m, con_m, plat_m, port_m, rep_m,
              quota_m):
        fails = mask & ~m
        fail_counts.append(jnp.sum(fails.astype(jnp.int32)))
        mask = mask & m
    fail_counts = reduce(jnp.stack(fail_counts))

    # capacity: how many tasks of this group each node can absorb
    cap = jnp.minimum(nodes.res_cap, jnp.minimum(group.k, K_CLAMP))
    cap = jnp.where(group.maxrep > 0,
                    jnp.minimum(cap, jnp.maximum(
                        group.maxrep - nodes.svc_tasks, 0)), cap)
    cap = jnp.where(group.port_limited, jnp.minimum(cap, 1), cap)
    cap = jnp.where(mask, jnp.maximum(cap, 0), 0)
    return mask, cap, fail_counts


def plan_group(nodes: NodeInputs, group: GroupInputs, L: int,
               reduce: Reduce = _identity,
               idx_offset: Optional[jnp.ndarray] = None,
               hier: Tuple = ()) -> jnp.ndarray:
    """Place a task group: returns x i32[N] = tasks assigned per node.

    Multi-stage hierarchical water-fill (reference semantics:
    scheduleNTasksOnSubtree equalizes branch totals level by level,
    scheduleNTasksOnNodes levels per-service counts):

      stage A: walk the spread-preference tree top-down; at each level the
               parent's allocation is water-filled over its child branches
               (loads = branch service-task totals, capacity = branch
               feasible capacity).  ``hier`` carries the upper levels as
               (seg_nodes i32[N], parent i32[L_d]) pairs, top level first;
               ``nodes.leaf`` is the deepest level with L segments.
      stage B: nodes within each leaf — level per-service counts
               (failure-down-weighted), tie-broken by total tasks.

    Returns (x i32[N] tasks per node, fail_counts i32[7] per-filter
    failure counts in pipeline order, spill bool scalar — True when a
    spread branch saturated and the caller should use the host path for
    exact reference parity).
    """
    mask, cap, fail_counts = feasibility_and_capacity(nodes, group, reduce)
    n = nodes.ready.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    if idx_offset is not None:
        idx = idx + idx_offset

    svc = jnp.clip(nodes.svc_tasks, 0, SVC_CLAMP)
    # The waterfill needs a true per-node e.  broadcast_to is a no-op for
    # today's full-width inputs; it future-proofs against callers shipping
    # broadcastable length-1 stand-ins for no-signal arrays (tried for H2D
    # savings and currently off — see the recompile trade-off note in
    # planner._build_device_inputs before re-enabling).
    e = jnp.broadcast_to(spread_score(nodes),
                         nodes.ready.shape).astype(jnp.int32)

    # ---- stage A: allocation down the branch hierarchy
    # branch load counts every valid node's service tasks (feasible or not),
    # matching nodeset.go:88-105 where tree.tasks accumulates per walked
    # node.  Sums ride f32 (overflow-safe, see docstring) and are clamped
    # back into the int32 search ranges: loads above LOAD_CLAMP are
    # equi-preferred, caps above k are equivalent to k.
    kk = jnp.minimum(group.k, K_CLAMP)
    svc_valid = jnp.where(nodes.valid, svc, 0)

    def branch_arrays(seg, n_segs):
        load = jnp.minimum(
            reduce(_seg_sum_f32(svc_valid, seg, n_segs)),
            float(LOAD_CLAMP)).astype(jnp.int32)
        raw_cap = reduce(_seg_sum_f32(cap, seg, n_segs))  # true capacity
        bcap = jnp.minimum(raw_cap,
                           kk.astype(jnp.float32)).astype(jnp.int32)
        return load, bcap, raw_cap

    # hier = (upper_levels, leaf_parent):
    #   upper_levels — tuple of (seg_nodes i32[N], parent i32[L_d]) pairs,
    #   top level first, for every level ABOVE the leaves;
    #   leaf_parent  — i32[L] mapping each leaf to its upper-level branch.
    upper_levels, leaf_parent = hier if hier else ((), None)

    k_parent = kk.reshape(1)   # the root's allocation
    parent_count = 1
    # branch-capacity binding detector: when a spread branch saturates
    # (allocation == capacity with capacity > 0 at a multi-branch level),
    # the host oracle's convergence loop (scheduler.py:738, mirroring
    # reference scheduler.go:772) redistributes with STALE branch counts
    # and order-biased remainders, producing lumpier distributions than
    # this water-fill's globally-even answer.  Rather than replicate that
    # sequential quirk on device, flag it: the planner routes flagged
    # groups to the host path, preserving exact reference parity.
    spill = jnp.zeros((), jnp.bool_)

    def level_spill(alloc, raw_cap):
        # a level diverges from the host only when SOME usable branch
        # truly saturates (allocation == its UNclamped capacity) while
        # ANOTHER usable branch does not — that is when the host loop's
        # stale-count redistribution kicks in.  Compare against the raw
        # capacity, not the k-clamped bcap: a lone branch absorbing the
        # whole group, or a fully saturated level (host and device agree
        # there), must not flag.
        af = alloc.astype(jnp.float32)
        usable = raw_cap > 0
        sat = usable & (af >= raw_cap)
        return jnp.any(sat) & jnp.any(usable & ~sat)

    for seg_nodes, parent in upper_levels:
        L_d = parent.shape[0]
        load, bcap, raw_cap = branch_arrays(seg_nodes, L_d)
        # stage-A waterfills run on [L_d]-shaped, fully-replicated arrays
        # (the reduce already happened in branch_arrays), so no cross-shard
        # reduce is needed even under shard_map
        k_parent = seg_waterfill(
            e=load, cap=bcap, tie=jnp.arange(L_d, dtype=jnp.int32),
            k_seg=k_parent, seg=parent, L=parent_count)
        if L_d > 1:
            spill = spill | level_spill(k_parent, raw_cap)
        parent_count = L_d

    if L == 1 and not upper_levels:
        _, branch_cap, _raw = branch_arrays(nodes.leaf, 1)
        k_branch = jnp.minimum(kk, branch_cap)
    else:
        load, bcap, raw_cap = branch_arrays(nodes.leaf, L)
        seg = leaf_parent if leaf_parent is not None \
            else jnp.zeros((L,), jnp.int32)
        k_branch = seg_waterfill(
            e=load, cap=bcap, tie=jnp.arange(L, dtype=jnp.int32),
            k_seg=k_parent, seg=seg, L=parent_count)
        if L > 1:
            spill = spill | level_spill(k_branch, raw_cap)

    # ---- stage B: nodes within each leaf branch
    tie = (jnp.clip(nodes.total_tasks, 0, TOTAL_CLAMP) << IDX_BITS) | idx
    x = seg_waterfill(e=e, cap=cap, tie=tie, k_seg=k_branch,
                      seg=nodes.leaf, L=L, reduce=reduce)
    return x, fail_counts, spill


@functools.partial(jax.jit, static_argnames=("L",))
def plan_group_jit(nodes: NodeInputs, group: GroupInputs, L: int,
                   hier: Tuple = ()) -> jnp.ndarray:
    return plan_group(nodes, group, L, hier=hier)


# ------------------------------------------------------- strategy seam
#
# The scoring stage is pluggable (scheduler/strategy.py registry):
# every strategy shares the SAME feasibility masks, bucket ladder and
# placement primitives (seg_waterfill / seg_packfill below); only the
# per-node score column differs.  Spread keeps riding plan_group /
# plan_fused untouched (its score is `spread_score` — the factored
# pre-seam computation, byte-identical by construction); the
# alternative strategies run through `plan_strategy_jit`, a separate
# jitted entry so spread's jit signatures cannot change.  Each device
# strategy's host oracle lives in scheduler/strategy.py: identical
# integer columns, identical integer formulas, bit-equal placements —
# the planner's breaker can demote any strategy group to the host
# oracle mid-tick without moving a single task.

def _downweight(failures: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(failures >= MAX_FAILURES,
                     jnp.clip(failures, 0, FAILURE_CLAMP), 0)


def spread_score(nodes: NodeInputs) -> jnp.ndarray:
    """The spread strategy's effective level: per-service count,
    failure-down-weighted (scheduler.go:708 nodeLess) — exactly the
    pre-seam inline computation, now the seam's default scorer."""
    svc = jnp.clip(nodes.svc_tasks, 0, SVC_CLAMP)
    return svc + _downweight(nodes.failures) * F_BIG


class StrategyInputs(NamedTuple):
    """Per-group strategy columns/parameters, densified host-side
    (exact int64 headroom divisions, mirrored by the host oracle).
    Unused members ship as zeros — the static ``strategy`` argument
    already separates jit signatures, so no Optional-field games."""

    hr_cpu: jnp.ndarray   # i32[N] cpu headroom in demand units
    hr_mem: jnp.ndarray   # i32[N] memory headroom in demand units
    hr_gen: jnp.ndarray   # i32[N] generic-resource headroom (min kind)
    weights: jnp.ndarray  # i32[4] weighted terms [spread,cpu,mem,gen]
    w1: jnp.ndarray       # i32[F, H] learned-scorer layer 1
    b1: jnp.ndarray       # i32[H]
    w2: jnp.ndarray       # i32[H]
    b2: jnp.ndarray       # i32[] scalar


def seg_packfill(key: jnp.ndarray, cap: jnp.ndarray,
                 k_seg: jnp.ndarray, seg: jnp.ndarray, L: int,
                 reduce: Reduce = _identity) -> jnp.ndarray:
    """Sequential (pack) fill within each segment: nodes take their
    full capacity in ascending ``key`` order until k is placed — the
    binpack placement primitive.  Keys must be unique per segment
    (callers pack the node index into the low bits).  Same
    threshold-search shape as seg_waterfill's tie stage, so it runs
    under shard_map with the identical ``reduce`` contract."""
    cap = cap.astype(jnp.int32)
    kf = k_seg.astype(jnp.float32)

    def body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo) // 2   # avoids int32 overflow of lo + hi
        cnt = reduce(_seg_sum_f32(
            jnp.where(key <= mid[seg], cap, 0), seg, L))
        ge = cnt >= kf
        return jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi)

    lo = jnp.full((L,), -1, jnp.int32)
    hi = jnp.full((L,), 1 << 30, jnp.int32)  # keys are < 2^30
    lo, hi = jax.lax.fori_loop(0, TIE_ITERS, body, (lo, hi))
    thr = hi   # minimal key threshold with fill >= k (2^30 infeasible)

    x = jnp.where(key < thr[seg], cap, 0)
    f = reduce(_seg_sum_f32(x, seg, L))
    # remainder is exact: whenever r > 0, f < k <= K_CLAMP < 2^24
    r = jnp.maximum(kf - f, 0.0)
    # keys are unique, so at most one element per segment sits AT the
    # threshold; by minimality of thr its capacity covers r
    grant = (key == thr[seg]) & (r[seg] > 0.0)
    return x + jnp.where(grant, jnp.minimum(
        cap, r[seg].astype(jnp.int32)), 0)


def _learned_score(nodes: NodeInputs, sin: StrategyInputs
                   ) -> jnp.ndarray:
    """Fixed-point MLP score — the device twin of
    scheduler/strategy.learned_score_host (identical int32 ops)."""
    f = jnp.stack([
        jnp.clip(nodes.svc_tasks, 0, FEAT_CLAMP),
        jnp.clip(nodes.total_tasks, 0, FEAT_CLAMP),
        jnp.clip(nodes.failures, 0, FEAT_CLAMP),
        jnp.clip(sin.hr_cpu, 0, FEAT_CLAMP),
        jnp.clip(sin.hr_mem, 0, FEAT_CLAMP),
        nodes.ready.astype(jnp.int32) * FEAT_CLAMP,
    ], axis=-1).astype(jnp.int32)                       # [N, F]
    # explicit multiply-add contractions (not jnp.dot): integer, exact,
    # and XLA maps the broadcast+reduce well on TPU
    h = jnp.sum(f[:, :, None] * sin.w1[None, :, :], axis=1) + sin.b1
    h = jnp.clip(jnp.right_shift(h, MLP_SHIFT), 0, FEAT_CLAMP)
    out = jnp.sum(h * sin.w2[None, :], axis=1) + sin.b2
    return jnp.clip(jnp.right_shift(out, MLP_SHIFT), 0, SCORE_CLAMP)


def strategy_score(nodes: NodeInputs, sin: StrategyInputs,
                   strategy: int) -> jnp.ndarray:
    """The pluggable scoring stage: per-node effective level (lower =
    preferred) for the waterfill strategies.  Formulas mirror
    scheduler/strategy.py's numpy oracles term for term."""
    if strategy == STRAT_WEIGHTED:
        w = sin.weights
        return (w[0] * jnp.clip(nodes.svc_tasks, 0, SVC_CLAMP)
                + w[1] * (HR_CLAMP - sin.hr_cpu)
                + w[2] * (HR_CLAMP - sin.hr_mem)
                + w[3] * (HR_CLAMP - sin.hr_gen)
                + _downweight(nodes.failures) * F_BIG)
    if strategy == STRAT_LEARNED:
        return (_learned_score(nodes, sin)
                + _downweight(nodes.failures) * F_BIG)
    return spread_score(nodes)


def plan_strategy(nodes: NodeInputs, group: GroupInputs,
                  sin: StrategyInputs, strategy: int,
                  reduce: Reduce = _identity,
                  idx_offset: Optional[jnp.ndarray] = None):
    """Place one task group under a non-spread strategy.  Shares the
    fused feasibility/capacity stage (and therefore the fail-count
    diagnostics) with plan_group; strategies ignore spread-preference
    trees (the strategy owns the scoring stage), so placement is one
    flat segment.  Returns the same (x, fail_counts, spill) triple as
    plan_group — spill is constantly False (no spread branches to
    saturate), so the planner's fetch path is shared unchanged."""
    mask, cap, fail_counts = feasibility_and_capacity(nodes, group,
                                                      reduce)
    n = nodes.ready.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    if idx_offset is not None:
        idx = idx + idx_offset
    seg = jnp.zeros(n, jnp.int32)
    kk = jnp.minimum(group.k, K_CLAMP).reshape(1)
    if strategy == STRAT_BINPACK:
        score = jnp.where(
            nodes.failures >= MAX_FAILURES,
            BP_CLAMP + 1 + jnp.clip(nodes.failures, 0, FAILURE_CLAMP),
            jnp.clip(nodes.res_cap, 0, BP_CLAMP))
        key = (score << IDX_BITS) | idx
        x = seg_packfill(key, cap, kk, seg, 1, reduce=reduce)
    else:
        e = jnp.broadcast_to(
            strategy_score(nodes, sin, strategy),
            nodes.ready.shape).astype(jnp.int32)
        tie = (jnp.clip(nodes.total_tasks, 0, TOTAL_CLAMP)
               << IDX_BITS) | idx
        x = seg_waterfill(e=e, cap=cap, tie=tie, k_seg=kk, seg=seg,
                          L=1, reduce=reduce)
    return x, fail_counts, jnp.zeros((), jnp.bool_)


@functools.partial(jax.jit, static_argnames=("strategy",))
def plan_strategy_jit(nodes: NodeInputs, group: GroupInputs,
                      sin: StrategyInputs, strategy: int):
    return plan_strategy(nodes, group, sin, strategy)


# ------------------------------------------------------- fused many-service
#
# One program for the WHOLE tick: every pending (service, spec-version)
# group is packed into shared static buckets (group slots G, constraint
# slots Cc, platform slots P, spread-leaf slots L, service slots S) and
# planned by a single XLA dispatch.  The groups are not independent — a
# group's placements feed the next group's per-service counts, total
# loads and remaining resources — so the fused program is a
# `lax.scan` over group slots carrying the cluster state (FusedCarry),
# which makes the sequential per-service semantics exact by
# construction: scan step g computes precisely what a standalone
# `plan_group` dispatch would see after groups 0..g-1 applied.
#
# Segment masking: each scan step scores ONLY its own group's inputs
# (constraints, spread leaves, failure down-weights are per group-slot
# rows; per-service counts live in `svc_acc[slot]` segments), so two
# groups in one batch can never cross-contaminate each other's
# feasibility or spread scoring — asserted by tests/test_fused.py.
#
# Resource accounting rides int64 (the host densifier's exact integer
# comparisons, see module docstring): callers trace/dispatch under
# `jax.experimental.enable_x64` (ops/fusedbatch.py) so avail//demand
# floor-divisions match numpy bit-for-bit.

class FusedShared(NamedTuple):
    """Run-wide node state, densified once per fused run."""

    valid: jnp.ndarray        # bool[N] padding mask
    ready: jnp.ndarray        # bool[N] READY && ACTIVE
    os_hash: jnp.ndarray      # i32[2, N] platform.os hash (hi, lo)
    arch_hash: jnp.ndarray    # i32[2, N] normalized arch hash (hi, lo)
    svc0: jnp.ndarray         # i32[S, N] base active tasks per service slot


class FusedGroups(NamedTuple):
    """Per-group inputs, stacked over the group axis G (scan xs).
    Padded slots carry k=0 (they place nothing and leave the carry
    untouched)."""

    k: jnp.ndarray            # i32[G] tasks to place (0 = padding slot)
    slot: jnp.ndarray         # i32[G] service slot into svc0/svc_acc
    maxrep: jnp.ndarray       # i32[G] max replicas per node (0 = off)
    cpu_d: jnp.ndarray        # i64[G] per-task nano-cpu reservation
    mem_d: jnp.ndarray        # i64[G] per-task memory reservation
    con_hash: jnp.ndarray     # i32[G, Cc, 2, N]
    con_op: jnp.ndarray       # i32[G, Cc] 0 ==, 1 !=, 2 disabled
    con_exp: jnp.ndarray      # i32[G, Cc, 2]
    plat: jnp.ndarray         # i32[G, P, 4] (-1 row sentinel = unused)
    failures: jnp.ndarray     # i32[G, N] recent failures for the group
    leaf: jnp.ndarray         # i32[G, N] spread leaf id (0 when no prefs)
    extra_mask: jnp.ndarray   # bool[G, N] plugin/volume masks
    # tenant-quota mask rows: all-False rows for groups whose tenant
    # was exhausted at admission; None when no group in the run is
    # quota-blocked (signature stability for quota-free workloads)
    quota_ok: Optional[jnp.ndarray] = None   # bool[G, N] or None


class FusedCarry(NamedTuple):
    """Cluster state threaded through the scan — and, across chunked
    dispatches of one run, kept device-resident between calls (the
    planner never fetches it; chunk i+1 consumes chunk i's carry as
    device arrays)."""

    total: jnp.ndarray        # i32[N] active tasks total
    cpu: jnp.ndarray          # i64[N] available nano-cpus
    mem: jnp.ndarray          # i64[N] available memory bytes
    svc_acc: jnp.ndarray      # i32[S, N] tasks placed per service slot
    #                           within this fused run


class FusedStrategy(NamedTuple):
    """Per-group strategy columns for a mixed-strategy fused run.
    ``sid``/``weights`` ride the scan xs next to FusedGroups; the
    learned-scorer parameters are run-wide and stay outside the scan
    (closed over).  Spread-only runs ship ``strat=None`` — the
    pre-strategy jit signatures, untouched."""

    sid: jnp.ndarray          # i32[G] strategy id (0 = spread)
    weights: jnp.ndarray      # i32[G, 4] weighted terms per group
    w1: jnp.ndarray           # i32[F, H] learned-scorer layer 1
    b1: jnp.ndarray           # i32[H]
    w2: jnp.ndarray           # i32[H]
    b2: jnp.ndarray           # i32[] scalar


def _fused_headroom(avail: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """In-scan headroom column in demand units: the exact int64 floor
    division planner._build_strategy_inputs applies host-side (callers
    dispatch under enable_x64), so a fused strategy group scores the
    same headrooms a per-group dispatch would densify after the
    preceding groups applied."""
    hr = jnp.clip(avail // jnp.maximum(d, 1), 0, HR_CLAMP)
    return jnp.where(d > 0, hr, HR_CLAMP).astype(jnp.int32)


def plan_fused(shared: FusedShared, groups: FusedGroups,
               carry: FusedCarry, L: int, reduce: Reduce = _identity,
               idx_offset: Optional[jnp.ndarray] = None,
               strat: Optional[FusedStrategy] = None):
    """Plan a fused batch of task groups in one program.

    Returns (x i32[G, N] tasks per node per group, fail_counts
    i32[G, 7], spill bool[G], carry' FusedCarry).  Placements are
    byte-identical to dispatching `plan_group` per group in order and
    applying each result before densifying the next — the scan carry
    IS that apply, restricted to the signals the kernel reads.

    ``strat`` (mixed-strategy runs): per-group strategy ids select the
    scoring stage in-scan via lax.switch over the four static-strategy
    programs — binpack/weighted/learned groups fuse alongside spread
    ones instead of breaking the run.  Headroom columns are computed
    from the carry (the same int64 divisions the host densifier runs),
    and hr_gen is the neutral HR_CLAMP because groups demanding
    generic resources never fuse (probe_group rejects them)."""
    no_ports = jnp.zeros_like(shared.valid)

    def step(state: FusedCarry, xs):
        if strat is None:
            g = xs
        else:
            g, g_sid, g_weights = xs
        # exact int64 resource math, matching the host densifier:
        # res_ok &= avail >= demand and cap = min(cap, avail // demand)
        # for each demanded resource, then clip to [0, K_CLAMP] in i32
        res_ok = shared.valid
        cap = jnp.full(state.cpu.shape, K_CLAMP, state.cpu.dtype)
        for avail, d in ((state.cpu, g.cpu_d), (state.mem, g.mem_d)):
            have = d > 0
            res_ok = res_ok & (~have | (avail >= d))
            cap = jnp.where(
                have, jnp.minimum(cap, avail // jnp.maximum(d, 1)), cap)
        res_cap = jnp.clip(cap, 0, K_CLAMP).astype(jnp.int32)
        svc = shared.svc0[g.slot] + state.svc_acc[g.slot]
        nodes = NodeInputs(
            valid=shared.valid, ready=shared.ready, res_ok=res_ok,
            res_cap=res_cap, svc_tasks=svc, total_tasks=state.total,
            failures=g.failures, leaf=g.leaf, os_hash=shared.os_hash,
            arch_hash=shared.arch_hash, port_conflict=no_ports,
            extra_mask=g.extra_mask,
            quota_ok=g.quota_ok if groups.quota_ok is not None else None)
        grp = GroupInputs(
            k=g.k, con_hash=g.con_hash, con_op=g.con_op,
            con_exp=g.con_exp, plat=g.plat, maxrep=g.maxrep,
            port_limited=jnp.zeros((), jnp.bool_))
        if strat is None:
            x, fail_counts, spill = plan_group(
                nodes, grp, L, reduce=reduce, idx_offset=idx_offset)
        else:
            sin = StrategyInputs(
                hr_cpu=_fused_headroom(state.cpu, g.cpu_d),
                hr_mem=_fused_headroom(state.mem, g.mem_d),
                hr_gen=jnp.full(res_cap.shape, HR_CLAMP, jnp.int32),
                weights=g_weights, w1=strat.w1, b1=strat.b1,
                w2=strat.w2, b2=strat.b2)

            def _spread():
                return plan_group(nodes, grp, L, reduce=reduce,
                                  idx_offset=idx_offset)

            def _strategy(sid_static):
                return plan_strategy(nodes, grp, sin, sid_static,
                                     reduce=reduce,
                                     idx_offset=idx_offset)

            x, fail_counts, spill = jax.lax.switch(
                jnp.clip(g_sid, 0, 3),
                [_spread,
                 lambda: _strategy(STRAT_BINPACK),
                 lambda: _strategy(STRAT_WEIGHTED),
                 lambda: _strategy(STRAT_LEARNED)])
        nxt = FusedCarry(
            total=state.total + x,
            cpu=state.cpu - x.astype(state.cpu.dtype) * g.cpu_d,
            mem=state.mem - x.astype(state.mem.dtype) * g.mem_d,
            svc_acc=state.svc_acc.at[g.slot].add(x))
        return nxt, (x, fail_counts, spill)

    xs_in = groups if strat is None \
        else (groups, strat.sid, strat.weights)
    carry_out, (xs, fcs, spills) = jax.lax.scan(step, carry, xs_in)
    return xs, fcs, spills, carry_out


@functools.partial(jax.jit, static_argnames=("L",))
def plan_fused_jit(shared: FusedShared, groups: FusedGroups,
                   carry: FusedCarry, L: int,
                   strat: Optional[FusedStrategy] = None):
    return plan_fused(shared, groups, carry, L, strat=strat)


# --------------------------------------------------------- pipeline stages
#
# The jitted entry above is ASYNC-DISPATCHED: calling it (stage 1)
# enqueues the XLA program and returns device arrays immediately; the
# host blocks only when it reads their values.  The pipelined scheduler
# exploits exactly this split — dispatch group i+1's plan (any plan_fn
# with plan_group_jit's signature, incl. the mesh-sharded one), run
# group i's host commit while the device computes, then fetch — with the
# two stages wrapped in the ``plan.dispatch`` / ``plan.d2h`` spans the
# overlap metrics are built from (ops/planner.py dispatch_group /
# fetch_group).

def fetch_plan(arrays):
    """Stage 2: one blocking D2H round-trip for a dispatched plan's
    outputs.  Fetch everything in one call — transfer latency dominates
    over tunneled links, so never fetch twice.  Works for single-device
    and mesh-sharded (shard_map) outputs alike.

    This is THE accounted D2H seam: every fetched byte lands in the
    device-telemetry transfer ledger (host-side nbytes of the numpy
    results — no device introspection, so accounting cannot change
    placements)."""
    out = jax.device_get(arrays)
    from ..obs import devicetelemetry as _devtel
    _devtel.note_d2h("fetch", _devtel.tree_nbytes(out))
    return out


@jax.jit
def feasibility_jit(nodes: NodeInputs, group: GroupInputs):
    """Mask + capacity only — validates preassigned (global-service)
    tasks against their fixed nodes in one fused call instead of a
    per-task host filter walk (reference: scheduler.go:646
    taskFitNode runs the same pipeline the planner does)."""
    mask, cap, fail_counts = feasibility_and_capacity(
        nodes, group, lambda v: v)
    return mask, cap, fail_counts


# ----------------------------------------------------------- gang admission
#
# Gang scheduling (scheduler/gang.py) needs ONE device answer per gang:
# can the cluster absorb all k members simultaneously?  That is the
# fused filter pipeline's capacity column reduced to a single
# comparison — sum(cap) >= k — so the kernel reuses
# feasibility_and_capacity verbatim and inherits its numeric contract:
# per-node cap <= K_CLAMP, and the f32 total is exact below 2^24 while
# anything above keeps enough relative accuracy to stay far beyond
# K_CLAMP, so the comparison is always decided correctly (see module
# docstring).

def gang_fit(nodes: NodeInputs, group: GroupInputs,
             reduce: Reduce = _identity):
    """All-members-feasible reduction: (fit bool scalar, fail_counts
    i32[8]).  ``fit`` is True iff the summed per-node capacity covers
    the whole gang; the per-filter failure counts feed the same
    ``no suitable node (...)`` deferral diagnostics the plan path
    emits."""
    mask, cap, fail_counts = feasibility_and_capacity(nodes, group, reduce)
    total = reduce(jnp.sum(cap.astype(jnp.float32)))
    kf = jnp.minimum(group.k, K_CLAMP).astype(jnp.float32)
    return total >= kf, fail_counts


@jax.jit
def gang_fit_jit(nodes: NodeInputs, group: GroupInputs):
    return gang_fit(nodes, group, lambda v: v)


@jax.jit
def gang_fit_fused_jit(nodes: NodeInputs, groups: GroupInputs):
    """Fused gang route: every array in ``nodes``/``groups`` carries a
    leading gang axis G (host-side stack of the same per-gang
    densifications the per-gang route uses; ``quota_ok`` must be
    stacked for all gangs or None for all).  Each gang is judged
    against the same base cluster state — atomic admission re-walks
    gangs in deterministic order and re-validates in the commit
    transaction, so the precheck is deliberately independent per
    gang."""
    return jax.vmap(lambda n, g: gang_fit(n, g, lambda v: v))(
        nodes, groups)
