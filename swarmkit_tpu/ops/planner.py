"""TPU batch planner: plugs the device kernel into the scheduler seam.

Implements the ``batch_planner`` protocol consumed by
scheduler.Scheduler._schedule_task_group: given a task group, either place
the whole group on device and return True, or return False to fall back to
the host (oracle) path.

Falls back for features the device path does not model yet (documented
parity waivers): CSI volume mounts, node.ip constraints, named (non-
discrete) generic resources in *node* inventories, and spread-preference
trees deeper than 4 levels.  Multi-level spread (up to 4 levels) runs on
device via the kernel's hierarchical stage-A water-fill.

Small groups route to the host path: a device launch costs a fixed
round-trip (measured adaptively; ~100ms over a tunneled TPU, far less
locally) while the host oracle costs tens of microseconds per task, so
below the measured break-even the pipeline seam simply keeps the group on
the host.  Large groups — where the kernel's margin is 30x+ per decision —
go to the device.

Densification builds SoA arrays from the scheduler's NodeSet mirror.  The
group-independent node columns are built once per tick (begin_tick), kept
in sync by the apply phase's batched per-node updates, and invalidated
whenever a host-path fallback (which mutates NodeInfos directly) occurs —
so a tick of many small groups pays O(N) once, not O(N x groups).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.objects import Meta, Task
from ..models.types import (
    GenericResourceKind, MountType, NodeAvailability, NodeState, PublishMode,
    Version, now,
)
from ..scheduler import constraint as constraint_mod
from ..scheduler import strategy as strategy_mod
from ..scheduler.filters import normalize_arch, _references_volume_plugin
from ..scheduler.nodeinfo import NodeInfo
from ..models.types import TaskState, TaskStatus
from ..obs import devicetelemetry as _devtel
from ..obs import planes as _planes
from ..obs.trace import tracer
from ..utils.metrics import registry as _metrics
from . import fusedbatch
from .fusedbatch import (
    CC_BUCKETS as _CC_BUCKETS, P_BUCKETS as _P_BUCKETS,
    SENTINEL as _SENTINEL, bucket as _bucket, l_bucket as _l_bucket,
    n_bucket as _n_bucket, split_hash as _split_hash,
)
from .hashing import str_hash
from .kernel import (
    GroupInputs, K_CLAMP, NodeInputs, StrategyInputs, fetch_plan,
    gang_fit_fused_jit, gang_fit_jit, plan_fused_jit, plan_group_jit,
    plan_strategy_jit,
)

log = logging.getLogger("tpu-planner")

# cached Timer references (Registry.reset() resets in place)
_PLAN_TIMER = _metrics.timer("swarm_planner_plan_latency")
_COMPILE_TIMER = _metrics.timer("swarm_planner_compile_latency")


def _jit_cache_size(fn) -> Optional[int]:
    """Compiled-signature count of a jitted callable, or None when the
    runtime does not expose it (then compile detection is off rather
    than guessed — the whole point is observation, not inference)."""
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is None:
        return None
    try:
        return cache_size()
    except Exception:
        return None


def _bucket_label(nodes_in, group_in, L: int, hier) -> str:
    """Stable name for one static jit signature: node bucket, constraint
    slots, platform slots, spread leaf bucket, spread depth.  Bounded
    cardinality — every component comes from a fixed bucket ladder."""
    depth = len(hier[0]) + 1 if hier else 0
    q = "_q1" if nodes_in.quota_ok is not None else ""
    return (f"nb{nodes_in.valid.shape[0]}_cc{group_in.con_hash.shape[0]}"
            f"_p{group_in.plat.shape[0]}_L{L}_h{depth}{q}")


def _observe_compile(fn, bucket: str, cache_before: Optional[int],
                     dt: float) -> float:
    """Count an XLA cache miss when the jit cache grew across one call:
    a ``swarm_planner_compiles{bucket=...}`` counter tick, a compile
    timer observation, and a retroactive ``plan.compile`` span — the
    explanation trail for ``shape_cost_x``/bench variance swings.

    Doubles as THE compile-cache ledger feed: every dispatch lands in
    the per-signature hit/miss registry (obs/devicetelemetry.py), so
    "compiles 0 in the timed window" is auditable per-bucket.  Returns
    the retro-measured compile seconds (0.0 on a hit) for the caller's
    kernel-ledger row."""
    after = _jit_cache_size(fn)
    if cache_before is None or after is None:
        return 0.0
    if after <= cache_before:
        _devtel.note_cache_hit(bucket)
        return 0.0
    _metrics.counter(f'swarm_planner_compiles{{bucket="{bucket}"}}',
                     after - cache_before)
    _COMPILE_TIMER.observe(dt)
    _devtel.note_compile(bucket, dt, after - cache_before)
    # under a virtual clock (the simulator) the wall-clock compile
    # duration would be the ONLY nondeterministic bytes in an otherwise
    # seed-pure span trace: keep the event, zero the duration
    from ..models.types import time_source_installed
    tracer.record_complete("plan.compile", "plan",
                           0.0 if time_source_installed() else dt,
                           bucket=bucket)
    return dt


# shape-bucket helpers live in ops/fusedbatch.py (single source for the
# per-group and fused paths); the module-private names above are aliases


def _fast_assign(task: Task, node_id: str, status) -> Task:
    """Minimal assignment clone for the columnar commit hot path.

    Equivalent to ``task.copy()`` + set node_id/status, minus the wasted
    copy of the status we immediately replace.  ``status`` may be shared
    across the whole group: stored/mirrored objects follow the
    replace-don't-mutate convention (Task.copy always copies status before
    any mutation), so structural sharing is safe.
    """
    new = object.__new__(Task)
    d = new.__dict__
    d.update(task.__dict__)
    m = task.meta
    new.meta = Meta(Version(m.version.index), m.created_at, m.updated_at)
    new.status = status
    new.node_id = node_id
    new.networks = list(task.networks)
    new.assigned_generic_resources = []
    new.volumes = list(task.volumes)
    return new


def _probe_inputs():
    nb = 1024
    valid = np.ones(nb, bool)
    nodes = NodeInputs(
        valid=valid, ready=valid.copy(),
        res_ok=valid.copy(), res_cap=np.full(nb, 8, np.int32),
        svc_tasks=np.zeros(nb, np.int32), total_tasks=np.zeros(nb, np.int32),
        failures=np.zeros(nb, np.int32), leaf=np.zeros(nb, np.int32),
        os_hash=np.zeros((2, nb), np.int32),
        arch_hash=np.zeros((2, nb), np.int32),
        port_conflict=np.zeros(nb, bool), extra_mask=np.ones(nb, bool))
    group = GroupInputs(
        k=np.int32(8), con_hash=np.zeros((1, 2, nb), np.int32),
        con_op=np.full(1, 2, np.int32), con_exp=np.zeros((1, 2), np.int32),
        plat=np.full((1, 4), -1, np.int32), maxrep=np.int32(0),
        port_limited=np.bool_(False))
    return nodes, group


BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN = 0, 1, 2
_BREAKER_NAMES = {BREAKER_CLOSED: "closed", BREAKER_HALF_OPEN: "half-open",
                  BREAKER_OPEN: "open"}


class PlannerBreaker:
    """Degraded-mode circuit breaker for the device path.

    N consecutive device dispatch/fetch failures trip the breaker OPEN:
    every group routes to the host oracle (placements stay valid, the
    tick never fails) until the cooldown elapses.  The breaker then goes
    HALF-OPEN and admits a single probe group; a successful probe closes
    it, a failed probe re-opens it with a doubled (capped) cooldown.
    Successful closes decay the accumulated cooldown back toward the
    base, so a device that recovers cleanly is re-trusted quickly while
    a flapping one backs off geometrically.

    State is exported as the ``swarm_planner_breaker_state`` gauge
    (0=closed, 1=half-open, 2=open) — judged by the ``planner_breaker``
    SLO check in obs/health — and every trip lands in the flight
    recorder.  Time is read through ``models.types.now()`` so the sim
    drives the cooldown deterministically.
    """

    def __init__(self, threshold: int = 3, cooldown: float = 30.0,
                 max_cooldown: float = 480.0):
        self.threshold = max(1, threshold)
        self.base_cooldown = cooldown
        self.max_cooldown = max_cooldown
        self._state = BREAKER_CLOSED
        self._failures = 0          # consecutive, resets on success
        self._cooldown = cooldown
        self._open_until = 0.0
        self._probe_inflight = False
        self.stats = {"trips": 0, "probes": 0, "failures": 0}
        self._export()

    def _export(self) -> None:
        _metrics.gauge("swarm_planner_breaker_state", self._state)

    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return _BREAKER_NAMES[self._state]

    def allow_device(self) -> bool:
        """Gate one group's device dispatch.  OPEN past its cooldown
        flips to HALF-OPEN and admits exactly one probe; every other
        caller stays on the host until the probe resolves."""
        if self._state == BREAKER_CLOSED:
            return True
        if self._state == BREAKER_OPEN:
            if now() < self._open_until:
                return False
            self._state = BREAKER_HALF_OPEN
            self._probe_inflight = False
            self._export()
        # HALF_OPEN: single probe at a time
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        self.stats["probes"] += 1
        _metrics.counter("swarm_planner_breaker_probes")
        return True

    def abort_probe(self) -> None:
        """The admitted group never reached the device (routed to host
        for an unrelated reason): release the probe slot unchanged."""
        self._probe_inflight = False

    def record_success(self) -> None:
        self._failures = 0
        if self._state == BREAKER_HALF_OPEN:
            self._probe_inflight = False
            self._state = BREAKER_CLOSED
            # decay the accumulated backoff toward the base: a clean
            # recovery is re-trusted, a flapper keeps most of its penalty
            self._cooldown = max(self.base_cooldown, self._cooldown / 2.0)
            self._export()
            log.info("planner breaker closed (device recovered)")

    def record_failure(self) -> None:
        self.stats["failures"] += 1
        if self._state == BREAKER_HALF_OPEN:
            # failed probe: back off harder
            self._cooldown = min(self._cooldown * 2.0, self.max_cooldown)
            self._trip()
            return
        self._failures += 1
        if self._state == BREAKER_CLOSED \
                and self._failures >= self.threshold:
            self._trip()

    def _trip(self) -> None:
        self._state = BREAKER_OPEN
        self._probe_inflight = False
        self._failures = 0
        self._open_until = now() + self._cooldown
        self.stats["trips"] += 1
        _metrics.counter("swarm_planner_breaker_trips")
        self._export()
        log.warning("planner breaker OPEN for %.1fs: device path "
                    "degraded to host fallback", self._cooldown)
        from ..obs.flightrec import flightrec
        flightrec.note(f"planner breaker tripped open "
                       f"(cooldown {self._cooldown:.1f}s)")


class _InFlightPlan:
    """One dispatched-but-unfetched device plan: everything fetch_group
    needs to finish the group once the device triple lands."""

    __slots__ = ("sched", "t", "task_group", "decisions", "built",
                 "plan_t0", "arrays", "bucket", "route")

    def __init__(self, sched, t, task_group, decisions, built, plan_t0,
                 arrays, bucket="", route="group"):
        self.sched = sched
        self.t = t
        self.task_group = task_group
        self.decisions = decisions
        self.built = built
        self.plan_t0 = plan_t0
        self.arrays = arrays
        # kernel-ledger attribution for the fetch stage (the dispatch
        # stage noted its half under the same key)
        self.bucket = bucket
        self.route = route


class TPUPlanner:
    def __init__(self, plan_fn=None, fused_plan_fn=None, mesh=None):
        # plan_fn(nodes: NodeInputs, group: GroupInputs, L: int, hier)
        # -> (x i32[N], fail_counts i32[7], spill bool); hier carries
        # multi-level
        # spread segments (() for flat).  Defaults to the single-device jit
        # kernel; parallel/sharded.py provides a mesh-sharded
        # implementation with the same signature.
        #
        # SWARM_PLANNER_MESH=<D> shards the node axis over the first D
        # devices (parallel/sharded.py ShardedPlanFn drives both the
        # per-group and fused kernels); explicit plan_fn/mesh args win
        # over the env knob.
        import os as _os
        if plan_fn is None and fused_plan_fn is None and mesh is None:
            from ..parallel.sharded import mesh_from_env
            mesh = mesh_from_env()
        if mesh is not None:
            from ..parallel.sharded import ShardedPlanFn
            sharded = ShardedPlanFn(mesh)
            plan_fn = plan_fn or sharded
            fused_plan_fn = fused_plan_fn or sharded
        self.mesh = mesh
        self._plan_fn = plan_fn or plan_group_jit
        # fused entry: an object exposing .fused(shared, groups, carry,
        # L) (+ optional .prepare_fused) — a ShardedPlanFn, or None for
        # the single-device kernel.  A ShardedPlanFn passed as plan_fn
        # serves both paths so the mesh is used consistently.
        if fused_plan_fn is None and hasattr(self._plan_fn, "fused"):
            fused_plan_fn = self._plan_fn
        self._fused_fn = fused_plan_fn
        # fused many-service batching (the one-program-per-tick path);
        # SWARM_FUSED_PLANNER=0 reverts to per-group dispatches.  An
        # injected plan_fn WITHOUT a fused twin owns the device path
        # entirely: fusing around it with the default kernel would
        # bypass the injected implementation (mesh fns, test stubs)
        self.fused_enabled = \
            _os.environ.get("SWARM_FUSED_PLANNER", "") != "0" \
            and (plan_fn is None or self._fused_fn is not None)
        self._fused_dead = False     # set on fused errors: rest of the
        #                              tick rides the per-group path
        self._fused_active = None    # in-flight FusedRun (tick aborts)
        self._tick_ts = None         # failure-window ts frozen per tick
        self.last_explanation = ""
        self.stats = {"groups_planned": 0, "groups_fallback": 0,
                      "groups_small_to_host": 0,
                      "tasks_planned": 0, "plan_seconds": 0.0}
        # measured fixed launch overhead (dispatch + D2H round-trip on a
        # minimal workload) vs. the host oracle's per-task cost: groups too
        # small to amortize a device round-trip stay on the host path
        self._launch_overhead = None
        self.host_cost_per_task = 50e-6
        # set False to force every supported group onto the device (bench
        # warm-ups, dryruns, deployments with local sub-ms D2H)
        self.enable_small_group_routing = True
        # per-tick cache of group-independent node columns; built on
        # begin_tick, updated incrementally by the apply phase, invalidated
        # by host-path fallbacks (which mutate NodeInfos behind our back)
        self._cache = None
        # streaming scheduler (ops/streaming.py): the node columns above
        # — and their device copies — stay RESIDENT across ticks and
        # refresh from the scheduler's dirty-set tracker in O(churn);
        # the full O(cluster) rebuild demotes to the counted fallbacks.
        # SWARM_STREAMING_PLANNER=0 reverts to per-tick rebuilds.
        self.streaming_enabled = \
            _os.environ.get("SWARM_STREAMING_PLANNER", "") != "0"
        self._streaming = None
        # degraded-mode circuit breaker: consecutive device failures trip
        # the whole planner to host fallback instead of failing ticks
        self.breaker = PlannerBreaker()
        # FIFO in-flight queue for the dispatch/fetch pipeline split:
        # plans dispatched via dispatch_group wait here until fetch_group
        # blocks on their D2H.  At most ONE plan may be in flight (the
        # dispatch_group guard): group i+1's input columns depend on
        # group i's apply, so the pipelined scheduler overlaps the
        # in-flight plan with group COMMITS (bounded by the scheduler's
        # pipeline_depth), never with another plan.
        self._inflight: deque = deque()

        # device-plane saturation probe (obs/planes.py): dispatch-queue
        # depth read lazily at window-roll time.  plane() resolved per
        # call — planes.reset() rebinds the table; weakref so the probe
        # never pins a dead planner; last-constructed planner owns it
        # (same discipline as raft/scheduler).
        import weakref
        _ref = weakref.ref(self)
        _planes.plane(_planes.DEVICE).set_probe(
            lambda: ({"depth": float(len(_ref()._inflight))}
                     if _ref() is not None else {}))

    # ------------------------------------------------------------- accounting

    # routing-counter keys -> the route label exported on
    # swarm_planner_groups{route=...}; every increment goes through
    # _count so the stats dict and the metrics registry can never
    # disagree (bench reads the registry)
    _ROUTE = {"groups_planned": "device",
              "groups_fused": "fused",
              "groups_fallback": "fallback",
              "groups_small_to_host": "host_small",
              "groups_spill_to_host": "spill",
              "groups_breaker_to_host": "breaker",
              "groups_strategy_host": "strategy_host"}

    def _count(self, key: str, delta: int = 1) -> None:
        self.stats[key] = self.stats.get(key, 0) + delta
        route = self._ROUTE.get(key)
        if route is not None:
            _metrics.counter(f'swarm_planner_groups{{route="{route}"}}',
                             delta)
        else:
            _metrics.counter(f"swarm_planner_{key}", delta)

    def _observe_plan(self, dt: float) -> None:
        self.stats["plan_seconds"] += dt
        _PLAN_TIMER.observe(dt)

    @staticmethod
    def _note_inflight(dt: float) -> None:
        """Retroactive ``plan.inflight`` span covering one plan's whole
        dispatch→fetch window.  The d2h span alone under-reports hidden
        work: compute that finished WHILE the host applied/committed an
        earlier group leaves a near-zero d2h wait, which would read as
        "no overlap" exactly when overlap worked best.  The in-flight
        window is what the commit spans genuinely ran inside of —
        obs/report.py counts it toward plan_hidden_frac.  Zero-duration
        under a virtual clock, like plan.compile (seed-pure sim traces).
        """
        from ..models.types import time_source_installed
        tracer.record_complete("plan.inflight", "plan",
                               0.0 if time_source_installed() else dt)

    def _call_plan_fn(self, nodes_in, group_in, L, hier):
        """Every device-plan dispatch goes through here so XLA cache
        misses are *observed* per static shape bucket (jit cache-size
        delta around the call), not inferred from timing swings.  The
        dispatch also lands in the device kernel ledger with its input
        columns' H2D bytes (host-side nbytes — the implicit
        numpy->device transfer at the jit boundary)."""
        import time as _time
        bucket = _bucket_label(nodes_in, group_in, L, hier)
        _devtel.note_h2d("group_inputs",
                         _devtel.tree_nbytes((nodes_in, group_in, hier)))
        before = _jit_cache_size(self._plan_fn)
        t0 = _time.perf_counter()
        out = self._plan_fn(nodes_in, group_in, L, hier)
        dt = _time.perf_counter() - t0
        comp = _observe_compile(self._plan_fn, bucket, before, dt)
        _devtel.note_kernel(bucket, "group", dispatch_s=dt,
                            compile_s=comp, task_rows=int(group_in.k),
                            node_rows=nodes_in.valid.shape[0])
        return out

    def _call_strategy_fn(self, nodes_in, group_in, sin, sinfo):
        """Strategy-kernel dispatch twin of ``_call_plan_fn``: same
        compile observation, per-strategy bucket suffix (each static
        strategy id is its own jit signature)."""
        import time as _time
        bucket = (_bucket_label(nodes_in, group_in, 1, ())
                  + f"_st{sinfo.sid}")
        _devtel.note_h2d("group_inputs",
                         _devtel.tree_nbytes((nodes_in, group_in, sin)))
        sfn = getattr(self._plan_fn, "strategy", None)
        probe = self._strategy_jit_probe()
        before = _jit_cache_size(probe)
        t0 = _time.perf_counter()
        if sfn is not None:
            out = sfn(nodes_in, group_in, sin, sinfo.sid)
        else:
            out = plan_strategy_jit(nodes_in, group_in, sin, sinfo.sid)
        dt = _time.perf_counter() - t0
        comp = _observe_compile(probe, bucket, before, dt)
        _devtel.note_kernel(bucket, "strategy", dispatch_s=dt,
                            compile_s=comp, task_rows=int(group_in.k),
                            node_rows=nodes_in.valid.shape[0],
                            strategy_id=sinfo.sid)
        return out

    def _build_strategy_inputs(self, built, t, sinfo) -> StrategyInputs:
        """Densify the strategy-seam columns for one group: per-resource
        headroom in demand units (exact int64 floor divisions — the
        host oracle's build_host_columns applies the identical per-row
        formula), the per-service weight vector, and the learned
        scorer's fixed artifact weights.  Unused members ship as zeros;
        the static strategy id keeps signatures apart."""
        (infos, n, nb, valid, cpu, mem, total, _nodes_in, _group_in,
         _L, _hier, cpu_d, mem_d, gen_wanted, _port_limited) = built
        HR = strategy_mod.HR_CLAMP
        if cpu_d > 0:
            hr_cpu = np.clip(cpu // cpu_d, 0, HR).astype(np.int32)
        else:
            hr_cpu = np.full(nb, HR, np.int32)
        if mem_d > 0:
            hr_mem = np.clip(mem // mem_d, 0, HR).astype(np.int32)
        else:
            hr_mem = np.full(nb, HR, np.int32)
        hr_gen = np.full(nb, HR, np.int32)
        if gen_wanted:
            for i, info in enumerate(infos):
                gen_min = HR
                for g in gen_wanted:
                    avail = 0
                    for r in info.available_resources.generic:
                        if r.kind == g.kind:
                            avail += (1 if r.res_type
                                      == GenericResourceKind.NAMED
                                      else r.value)
                    gen_min = min(gen_min,
                                  int(min(max(avail // g.value, 0), HR)))
                hr_gen[i] = gen_min
        if sinfo.uses_weights:
            weights = strategy_mod.weights_of(t)
        else:
            weights = np.zeros(4, np.int32)
        if sinfo.uses_learned:
            w1, b1, w2, b2 = strategy_mod.learned_params()
        else:
            f = len(strategy_mod.MLP_FEATURES)
            w1 = np.zeros((f, 1), np.int32)
            b1 = np.zeros(1, np.int32)
            w2 = np.zeros(1, np.int32)
            b2 = np.zeros((), np.int32)
        return StrategyInputs(hr_cpu=hr_cpu, hr_mem=hr_mem,
                              hr_gen=hr_gen, weights=weights,
                              w1=w1, b1=b1, w2=w2,
                              b2=np.asarray(b2, np.int32))

    # ------------------------------------------------------- per-tick caching

    def begin_tick(self, sched) -> None:
        self._in_tick = True
        self._fused_dead = False
        # one failure-window timestamp for the whole tick: the fused run
        # stamps its down-weights once, so the per-group path must read
        # the same instant or a failure aging out mid-tick breaks the
        # placement parity contract under a wall clock
        self._tick_ts = now()
        st = self._streaming_for(sched)
        if st is not None:
            self._cache = st.refresh(sched)
        else:
            self._cache = self._build_columns(sched)

    def _streaming_for(self, sched):
        """The resident-state plane when it may serve this scheduler:
        hatch on AND the scheduler carries the dirty-set delta feed
        (scheduler/deltatrack.py).  Lazily constructed — planners that
        only ever see tracker-less harnesses never pay for it."""
        if not self.streaming_enabled \
                or getattr(sched, "delta", None) is None:
            return None
        mesh = self.mesh \
            or getattr(self._plan_fn, "mesh", None) \
            or getattr(self._fused_fn, "mesh", None)
        if self._streaming is None:
            from .streaming import ResidentState
            self._streaming = ResidentState(self._node_value, mesh=mesh)
        else:
            # mesh teardown / shard-count change between ticks resyncs
            # the device tier (set_mesh is a no-op on identity)
            self._streaming.set_mesh(mesh)
        return self._streaming

    def _resident_for(self, cols):
        """The resident state iff ``cols`` came from it (identity on
        the infos list) — the guard every streaming fast path sits
        behind, so a planner fed foreign columns can never read stale
        resident caches."""
        st = self._streaming
        if st is not None and cols and cols[0] is st.infos:
            return st
        return None

    def streaming_snapshot(self):
        """Bench/obs surface: the ``streaming_*`` artifact fields."""
        st = self._streaming
        if st is None or not self.streaming_enabled:
            return {"enabled": False, "dirty_frac": None, "resyncs": 0,
                    "fallbacks": 0, "incremental_ticks": 0,
                    "full_ticks": 0, "rows": 0, "device_syncs": 0}
        return st.snapshot()

    def end_tick(self) -> None:
        self._in_tick = False
        self._tick_ts = None
        if self._fused_active is not None:   # abandoned run (aborted tick)
            self.abort_fused_run(self._fused_active)
        self._cache = None

    def fail_ts(self):
        """Failure-window timestamp: frozen per tick so the fused and
        per-group paths count the same recent failures (see
        begin_tick); falls back to now() for out-of-tick densifies."""
        ts = self._tick_ts
        return ts if ts is not None else now()

    def _build_columns(self, sched):
        node_set = sched.node_set
        infos: List[NodeInfo] = list(node_set.nodes.values())
        n = len(infos)
        nb = _n_bucket(max(n, 1))
        valid = np.zeros(nb, bool)
        ready = np.zeros(nb, bool)
        cpu = np.zeros(nb, np.int64)
        mem = np.zeros(nb, np.int64)
        total = np.zeros(nb, np.int32)
        valid[:n] = True
        for i, info in enumerate(infos):
            node = info.node
            ready[i] = (node.status.state == NodeState.READY
                        and node.spec.availability == NodeAvailability.ACTIVE)
            cpu[i] = info.available_resources.nano_cpus
            mem[i] = info.available_resources.memory_bytes
            total[i] = info.active_tasks_count
        return [infos, n, nb, valid, ready, cpu, mem, total]

    # explanation builders, pipeline order (matches kernel fail_counts rows
    # and the host filters' Explain strings — filter.go)
    _EXPLAINERS = (
        lambda n: (f"{n} nodes not available for new tasks" if n != 1
                   else "1 node not available for new tasks"),
        lambda n: (f"insufficient resources on {n} nodes" if n != 1
                   else "insufficient resources on 1 node"),
        lambda n: (f"missing plugin on {n} nodes" if n != 1
                   else "missing plugin on 1 node"),
        lambda n: (f"scheduling constraints not satisfied on {n} nodes"
                   if n != 1
                   else "scheduling constraints not satisfied on 1 node"),
        lambda n: (f"unsupported platform on {n} nodes" if n != 1
                   else "unsupported platform on 1 node"),
        lambda n: (f"host-mode port already in use on {n} nodes" if n != 1
                   else "host-mode port already in use on 1 node"),
        lambda n: "max replicas per node limit exceed",
        # the quota mask column (scheduler/quota.py): must produce the
        # exact string the host QuotaFilter.explain does — err-string
        # parity between the paths is part of the differential contract
        lambda n: (f"over tenant quota on {n} nodes" if n != 1
                   else "over tenant quota on 1 node"),
    )

    def _explain(self, fail_counts: np.ndarray) -> str:
        pairs = [(int(c), ex) for c, ex in zip(fail_counts, self._EXPLAINERS)]
        pairs.sort(key=lambda p: -p[0])
        return "; ".join(ex(c) for c, ex in pairs if c > 0)

    # ------------------------------------------------------------ suitability

    def _supported(self, t: Task) -> bool:
        c = t.spec.container
        if c is not None:
            for m in c.mounts:
                if m.type == MountType.CSI:
                    return False  # volume scheduling stays on host
        placement = t.spec.placement
        if placement:
            prefs = [p for p in placement.preferences if p.spread]
            if len(prefs) > 4:
                return False  # absurdly deep spread tree: host path
            # node.ip constraints (exact AND CIDR) ride the hash/prefix
            # columns (constraint.ip_column_spec) — no longer a waiver
        res = t.spec.resources.reservations if t.spec.resources else None
        if res:
            for g in res.generic:
                if g.res_type != GenericResourceKind.DISCRETE:
                    return False
        return True

    # ---------------------------------------------------------- densification

    def _densify(self, sched, t: Task):
        """Build (or reuse) the per-tick SoA arrays from the NodeSet mirror.

        The node-level arrays (ready/cpu/mem/total, int64 for exact
        resource math) are group-independent and cached across the groups
        of one tick (begin_tick); per-service arrays (svc_tasks/failures)
        and constraint/platform/port columns are group-dependent and built
        per group.
        """
        if self._cache is not None:
            return self._cache
        st = self._streaming_for(sched)
        if st is not None:
            # O(churn): host-path mutations were hook-marked dirty, so
            # the resident columns refresh row-wise instead of rebuilding
            cols = st.refresh(sched)
        else:
            cols = self._build_columns(sched)
        if getattr(self, "_in_tick", False):
            # re-cache after an invalidation: the fresh columns already
            # reflect any host-path mutations
            self._cache = cols
        return cols

    _launch_overhead_shared: Optional[float] = None  # per-process link cost

    def _measure_launch_overhead(self) -> None:
        """Time a minimal warm launch: dispatch + compute-epsilon + D2H
        round-trip.  ~100ms over a tunneled TPU, ~1ms locally; this is the
        fixed cost a group must amortize to be worth the device.  The
        result is a property of the process's device link, so it is
        measured once and shared across planner instances — re-measuring
        per instance would spend two round-trips inside every tick that
        builds a fresh planner."""
        import time as _time
        import jax as _jax
        cls = type(self)
        if cls._launch_overhead_shared is not None:
            self._launch_overhead = cls._launch_overhead_shared
            return
        nodes_in, group_in = _probe_inputs()
        try:
            _jax.device_get(self._call_plan_fn(nodes_in, group_in, 1, ()))
            t0 = _time.perf_counter()
            probe_out = _jax.device_get(
                self._call_plan_fn(nodes_in, group_in, 1, ()))
            self._launch_overhead = _time.perf_counter() - t0
            _devtel.note_d2h("probe",
                             2 * _devtel.tree_nbytes(probe_out))
            # only successful measurements are shared: caching a failed
            # probe (0.0) would poison every future planner's break-even
            cls._launch_overhead_shared = self._launch_overhead
        except Exception:
            log.exception("launch-overhead probe failed")
            self._launch_overhead = 0.0

    def _below_break_even(self, n_tasks: int) -> bool:
        """True when a group is too small to amortize the device launch
        overhead.  The single predicate every routing site shares —
        dispatch_group, the host pre-validate path, and the fused-run
        probe must agree on it, or fused and per-group routing drift
        apart silently."""
        if not self.enable_small_group_routing:
            return False
        if self._launch_overhead is None:
            self._measure_launch_overhead()
        return (n_tasks * self.host_cost_per_task
                < 0.8 * self._launch_overhead)

    def _fallback(self) -> bool:
        # the host path will mutate NodeInfos the cached columns mirror
        self._count("groups_fallback")
        self._cache = None
        return False

    def _node_value(self, info: NodeInfo, key: str) -> str:
        node = info.node
        lk = key.lower()
        if lk == "node.id":
            return node.id
        if lk == "node.ip" or lk.startswith("node.ip/"):
            # hash/prefix column keys minted by constraint.ip_column_spec:
            # "node.ip" = canonical address, "node.ip/<p>" = canonical
            # containing network at prefix length p
            return constraint_mod.ip_node_value(
                node.status.addr if node.status else "", lk)
        if lk == "node.hostname":
            return node.description.hostname if node.description else ""
        if lk == "node.role":
            return "MANAGER" if node.spec.desired_role == 1 else "WORKER"
        if lk == "node.platform.os":
            return (node.description.platform.os
                    if node.description and node.description.platform else "")
        if lk == "node.platform.arch":
            return (node.description.platform.architecture
                    if node.description and node.description.platform else "")
        if lk.startswith(constraint_mod.NODE_LABEL_PREFIX):
            return node.spec.annotations.labels.get(
                key[len(constraint_mod.NODE_LABEL_PREFIX):], "")
        if lk.startswith(constraint_mod.ENGINE_LABEL_PREFIX):
            if node.description and node.description.engine:
                return node.description.engine.labels.get(
                    key[len(constraint_mod.ENGINE_LABEL_PREFIX):], "")
            return ""
        return None  # unknown key

    # ----------------------------------------------------------- entry point

    def schedule_group(self, sched, task_group: Dict[str, Task],
                       decisions) -> bool:
        """Serial entry point: dispatch + immediate fetch.  The pipelined
        scheduler calls the two stages separately (commit work runs
        between them); both paths share exactly this code, so pipelining
        cannot change placements."""
        handle = self.dispatch_group(sched, task_group, decisions)
        if handle is None:
            return False
        return self.fetch_group(handle)

    def dispatch_group(self, sched, task_group: Dict[str, Task],
                       decisions) -> Optional[_InFlightPlan]:
        """Pipeline stage 1: route, densify, and async-dispatch one
        group's device plan.  Returns an in-flight handle to finish with
        ``fetch_group``, or None when the group is not device-planned
        (the caller must run the host path; routing counters and column-
        cache invalidation have already been applied exactly as the
        serial path would).

        The handle's plan was built from the CURRENT mirror state: the
        caller must fetch-and-apply it before mutating mirrors or
        building another group's inputs (enforced below), otherwise the
        dispatched placement would be read against stale columns.
        """
        t = next(iter(task_group.values()))
        if not self._supported(t):
            self._fallback()
            return None
        sinfo = strategy_mod.resolve(strategy_mod.strategy_of(t))
        if sinfo is None:
            # unknown strategy name (written behind the API): the host
            # path serves it through the spread tree and counts the
            # strategy fallback
            self._fallback()
            return None
        if sinfo.sid != strategy_mod.STRAT_SPREAD \
                and self._plan_fn is not plan_group_jit \
                and not hasattr(self._plan_fn, "strategy"):
            # an injected plan_fn (test stubs) owns the device path and
            # has no strategy twin: the group rides its HOST ORACLE —
            # identical placements by the seam's bit-parity contract,
            # one densify on the host instead.  Mesh ShardedPlanFn
            # exposes .strategy and keeps non-spread groups on device.
            self._count("groups_strategy_host")
            self._cache = None   # host path mutates NodeInfos
            return None
        if not self.breaker.allow_device():
            # degraded mode: a sick device routes every group to the
            # host oracle until the breaker's cooldown/probe admits it
            self._count("groups_breaker_to_host")
            self._cache = None   # host path mutates NodeInfos
            return None
        if self._below_break_even(len(task_group)):
            self._count("groups_small_to_host")
            self.breaker.abort_probe()   # never reached the device
            self._cache = None   # host path mutates NodeInfos
            return None

        import time as _time
        _plan_t0 = _time.perf_counter()
        k = len(task_group)
        if k > K_CLAMP:  # beyond the kernel's 32-bit budget (see kernel.py)
            self.breaker.abort_probe()
            self._fallback()
            return None
        if self._inflight:
            self.breaker.abort_probe()
            raise RuntimeError(
                "dispatch_group with a plan already in flight: fetch it "
                "first (its apply feeds this group's input columns)")
        flat = sinfo.sid != strategy_mod.STRAT_SPREAD
        with tracer.span("plan.build_inputs", "plan", tasks=k):
            built = self._build_device_inputs(sched, t, k, flat=flat)
        if built is None:
            self.breaker.abort_probe()
            self._fallback()
            return None
        if built[1] == 0:   # no valid nodes densified
            self.breaker.abort_probe()
            return None
        nodes_in, group_in, L, hier = built[7], built[8], built[9], \
            built[10]
        try:
            with tracer.span("plan.dispatch", "plan", tasks=k):
                if flat:
                    sin = self._build_strategy_inputs(built, t, sinfo)
                    arrays = self._call_strategy_fn(nodes_in, group_in,
                                                    sin, sinfo)
                else:
                    arrays = self._call_plan_fn(nodes_in, group_in, L,
                                                hier)
        except Exception:
            # device dispatch failure degrades THIS group to the host
            # path and feeds the breaker — a sick device trips to
            # wholesale host fallback instead of failing the tick
            # (strategy groups land on their host oracle: bit-equal)
            log.exception("device dispatch failed; group routed to host")
            self._count("groups_device_error")
            self.breaker.record_failure()
            self._cache = None
            return None
        if flat:
            strategy_mod.count_group(sinfo.name, "device")
        bucket = _bucket_label(nodes_in, group_in, L, hier)
        if flat:
            bucket += f"_st{sinfo.sid}"
        handle = _InFlightPlan(sched, t, task_group, decisions, built,
                               _plan_t0, arrays, bucket=bucket,
                               route="strategy" if flat else "group")
        self._inflight.append(handle)
        return handle

    def _build_device_inputs(self, sched, t, k, flat=False):
        """Densify the cluster + one task-group spec into kernel inputs.
        Shared by group planning and preassigned validation.  Returns None
        when a static bucket overflows (caller falls back to the host
        path).  ``flat``: skip the spread-preference tree (non-spread
        strategies own the scoring stage — one flat segment)."""
        cols = self._densify(sched, t)
        infos, n, nb, valid, ready, cpu, mem, total = cols
        if n == 0:
            return (infos, 0, nb, valid, cpu, mem, total, None, None, 1,
                    (), 0, 0, [], False)
        # resident fast paths (ops/streaming.py): per-service counts,
        # failure rows, platform hashes, constraint hash columns and
        # flat spread leaves come from row-wise-maintained caches —
        # O(touched rows) instead of an O(cluster) Python loop per
        # group.  Values are byte-identical to the loops below by
        # construction (same per-row formulas); the loops remain as the
        # tracker-less/hatch-off path AND the differential oracle.
        st = self._resident_for(cols)

        # ---- per-service arrays.  NOTE: every input keeps its full node
        # shape even when it carries no signal — shrinking no-signal
        # arrays to broadcastable stand-ins was tried (saves ~40ms of H2D
        # per tick on a tunneled link) and reverted: each narrow/wide
        # combination is a distinct jit signature, so cluster-state flips
        # (first failure, first active task) and new spec shapes trigger
        # 20-40s XLA recompiles at runtime — a far worse trade.
        ts = self.fail_ts()
        sid = t.service_id
        failures = np.zeros(nb, np.int32)
        if st is not None:
            svc_tasks = st.svc_tasks_col(sched, sid)
            if st.fail_rows:
                st.fill_failures(failures, ts, t)
        else:
            svc_tasks = np.zeros(nb, np.int32)
            for i, info in enumerate(infos):
                c = info.active_tasks_count_by_service.get(sid, 0)
                if c:
                    svc_tasks[i] = c
                if info.recent_failures:
                    failures[i] = info.count_recent_failures(ts, t)

        # ---- constraints
        placement = t.spec.placement
        constraints = []
        if placement and placement.constraints:
            try:
                constraints = constraint_mod.parse(placement.constraints)
            except constraint_mod.InvalidConstraint:
                constraints = []
        cc = _bucket(len(constraints), _CC_BUCKETS)
        if cc is None:
            return None
        con_hash = np.zeros((cc, 2, nb), np.int32)
        con_op = np.full(cc, 2, np.int32)     # 2 = disabled
        con_exp = np.zeros((cc, 2), np.int32)
        if constraints:
            if st is not None:
                st.fill_constraints(sched, constraints, con_hash,
                                    con_op, con_exp)
            else:
                fusedbatch.fill_constraints(self._node_value, infos, n,
                                            constraints, con_hash,
                                            con_op, con_exp)

        # ---- platforms
        platforms = placement.platforms if placement else []
        pb = _bucket(max(len(platforms), 1), _P_BUCKETS)
        if pb is None:
            return None
        plat = np.full((pb, 4), -1, np.int32)
        fusedbatch.fill_platforms(platforms, plat)
        if platforms:
            if st is not None:
                os_hash, arch_hash = st.platform_hashes()
            else:
                os_hash, arch_hash = fusedbatch.node_platform_hashes(
                    infos, nb)
        else:
            os_hash = np.zeros((2, nb), np.int32)
            arch_hash = np.zeros((2, nb), np.int32)

        # ---- resources: exact int64 mask + capacity, computed host-side so
        # device decisions match the host oracle's integer comparisons
        res = t.spec.resources.reservations if t.spec.resources else None
        cpu_d = int(res.nano_cpus) if res else 0
        mem_d = int(res.memory_bytes) if res else 0
        gen_wanted = [g for g in (res.generic if res else [])]
        res_ok = valid.copy()
        res_cap = np.full(nb, K_CLAMP, np.int64)
        for avail, demand in ((cpu, cpu_d), (mem, mem_d)):
            if demand > 0:
                res_ok &= avail >= demand
                np.minimum(res_cap, avail // demand, out=res_cap)
        for g in gen_wanted:
            if g.value <= 0:
                continue
            gen_avail = np.zeros(nb, np.int64)
            for i, info in enumerate(infos):
                avail = 0
                for r in info.available_resources.generic:
                    if r.kind == g.kind:
                        avail += (1 if r.res_type == GenericResourceKind.NAMED
                                  else r.value)
                gen_avail[i] = avail
            res_ok &= gen_avail >= g.value
            np.minimum(res_cap, gen_avail // g.value, out=res_cap)
        res_cap = np.clip(res_cap, 0, K_CLAMP).astype(np.int32)

        # ---- host ports
        port_conflict = np.zeros(nb, bool)
        port_limited = False
        if t.endpoint:
            wanted = [(p.protocol, p.published_port)
                      for p in t.endpoint.ports
                      if p.publish_mode == PublishMode.HOST
                      and p.published_port]
            if wanted:
                port_limited = True
                for i, info in enumerate(infos):
                    if info.used_host_ports:
                        port_conflict[i] = any(
                            w in info.used_host_ports for w in wanted)

        # ---- plugins (volume/network/log drivers): host-side mask
        if fusedbatch.needs_plugins(t):
            extra_mask = fusedbatch.plugin_mask(t, infos, nb)
        else:
            extra_mask = np.ones(nb, bool)

        # ---- tenant quota mask column: materialized (all-False) only
        # for groups the ledger BLOCKED at admission — the frozen
        # verdict, never recomputed here (the group's own in-tick
        # charge must not flip it).  Unblocked groups ship None so the
        # quota-free jit signatures stay untouched.
        quota_ok = None
        if fusedbatch.group_quota_blocked(sched, t):
            quota_ok = np.zeros(nb, bool)

        # ---- spread preferences -> hierarchical branch ids.  Each level's
        # segment id identifies the node's branch path prefix; the kernel's
        # stage A equalizes allocations level by level (nodeset.go:50 tree)
        leaf = np.zeros(nb, np.int32)
        L = 1
        hier = ()
        prefs = [] if flat else \
            [p for p in (placement.preferences if placement else [])
             if p.spread]
        if len(prefs) == 1:
            # the common flat case: one pass keyed by the raw value
            # (resident leaf column when the streaming plane holds one)
            descriptor = prefs[0].spread.spread_descriptor
            if st is not None:
                leaf, n_values = st.flat_leaf(sched, descriptor)
            else:
                leaf, n_values = fusedbatch.flat_leaf(infos, nb,
                                                      descriptor)
            L = _l_bucket(n_values)
        elif prefs:
            from ..scheduler.nodeset import _pref_value
            descriptors = [p.spread.spread_descriptor for p in prefs]
            depth = len(descriptors)
            paths = []
            for info in infos:
                paths.append(tuple(_pref_value(info, d) or ""
                                   for d in descriptors))
            level_ids: List[Dict[tuple, int]] = []
            seg_arrays: List[np.ndarray] = []
            for di in range(depth):
                ids: Dict[tuple, int] = {}
                seg = np.zeros(nb, np.int32)
                for i, path in enumerate(paths):
                    seg[i] = ids.setdefault(path[:di + 1], len(ids))
                level_ids.append(ids)
                seg_arrays.append(seg)
            leaf = seg_arrays[-1]
            L = _l_bucket(max(len(level_ids[-1]), 1))
            if depth > 1:
                upper = []
                for di in range(depth - 1):
                    L_d = _l_bucket(max(len(level_ids[di]), 1))
                    parent = np.zeros(L_d, np.int32)
                    if di > 0:
                        for path, cid in level_ids[di].items():
                            parent[cid] = level_ids[di - 1][path[:di]]
                    upper.append((seg_arrays[di], parent))
                leaf_parent = np.zeros(L, np.int32)
                for path, cid in level_ids[-1].items():
                    leaf_parent[cid] = level_ids[-2][path[:depth - 1]]
                hier = (tuple(upper), leaf_parent)

        nodes_in = NodeInputs(
            valid=valid, ready=ready, res_ok=res_ok, res_cap=res_cap,
            svc_tasks=svc_tasks, total_tasks=total, failures=failures,
            leaf=leaf, os_hash=os_hash, arch_hash=arch_hash,
            port_conflict=port_conflict, extra_mask=extra_mask,
            quota_ok=quota_ok)
        group_in = GroupInputs(
            k=np.int32(k), con_hash=con_hash, con_op=con_op, con_exp=con_exp,
            plat=plat, maxrep=np.int32(
                placement.max_replicas if placement else 0),
            port_limited=np.bool_(port_limited))
        return (infos, n, nb, valid, cpu, mem, total, nodes_in, group_in,
                L, hier, cpu_d, mem_d, gen_wanted, port_limited)

    def _apply_assignments(self, sched, t, items, slots, infos,
                           decisions, cpu_d, mem_d, counts,
                           cpu, mem, total,
                           message="scheduler assigned task to node"
                           ) -> None:
        """Shared apply: clone+register the assigned tasks (C hot path
        when available) and do the per-NODE mirror arithmetic in batch.
        ``counts``: i32[nb] tasks placed per node column."""
        from ..scheduler.scheduler import SchedulingDecision

        from .. import native
        hp = native.get()
        all_tasks = sched.all_tasks
        # resident row lists when the streaming plane owns these infos
        # (identity-guarded) — kills two O(cluster) list builds per group
        st = self._streaming
        if st is not None and st.infos is not infos:
            st = None
        if getattr(sched, "block_mode", False):
            # columnar end-to-end: no per-task object materialization —
            # each group stages one (olds, nids, message) column triple and
            # commits as one array-shaped store call
            # (store.commit_task_block); mirrors keep the pre-assignment
            # object (membership + reservations are what they serve)
            node_id_by_i = st.node_ids if st is not None \
                else [info.node.id for info in infos]
            if hp is not None:
                task_dict_by_i = st.task_dicts if st is not None \
                    else [info.tasks for info in infos]
                olds, nids = hp.block_stage(items, slots, node_id_by_i,
                                            task_dict_by_i)
            else:
                olds, nids = [], []
                for (task_id, task), i in zip(items, slots):
                    olds.append(task)
                    nids.append(node_id_by_i[i])
                    infos[i].tasks[task_id] = task
            if olds:
                sched.block_draft.append((olds, nids, message))
        elif hp is not None:
            shared_status = TaskStatus(
                state=TaskState.ASSIGNED, timestamp=now(), message=message)
            node_id_by_i = st.node_ids if st is not None \
                else [info.node.id for info in infos]
            task_dict_by_i = st.task_dicts if st is not None \
                else [info.tasks for info in infos]
            hp.plan_apply(items, slots, node_id_by_i, task_dict_by_i,
                          shared_status, all_tasks, decisions,
                          SchedulingDecision)
        else:
            shared_status = TaskStatus(
                state=TaskState.ASSIGNED, timestamp=now(), message=message)
            for (task_id, task), i in zip(items, slots):
                info = infos[i]
                new_t = _fast_assign(task, info.id, shared_status)
                all_tasks[task_id] = new_t
                info.tasks[task_id] = new_t
                decisions[task_id] = SchedulingDecision(task, new_t)
        service_id = t.service_id
        idx = np.nonzero(counts)[0]
        if self._cache is not None and len(idx):
            # column-cache arithmetic stays vectorized; only the per-node
            # NodeInfo mirror below needs a Python loop
            hit = counts[idx]
            total[idx] += hit
            cpu[idx] -= hit.astype(np.int64) * cpu_d
            mem[idx] -= hit.astype(np.int64) * mem_d
        # the batched mirror arithmetic below bypasses the NodeInfo
        # mutation hooks: mark the touched rows dirty directly so the
        # resident device-input state refreshes them next absorb
        delta = getattr(sched, "delta", None)
        mark = delta.mark if delta is not None else None
        for i in idx.tolist():
            cnt = int(counts[i])
            info = infos[i]
            info.active_tasks_count += cnt
            svc_map = info.active_tasks_count_by_service
            svc_map[service_id] = svc_map.get(service_id, 0) + cnt
            ar = info.available_resources
            ar.nano_cpus -= cnt * cpu_d
            ar.memory_bytes -= cnt * mem_d
            if mark is not None:
                mark(info.node.id)

    def validate_preassigned(self, sched, tasks, decisions) -> list:
        """Validate preassigned tasks (same service) against their FIXED
        nodes in one fused device call (reference: scheduler.go:646
        taskFitNode, which walks the same filter pipeline per task).

        Admits each task iff its node passes the feasibility mask and has
        remaining capacity after earlier tasks in this batch claimed it.
        Admitted tasks are written into ``decisions`` (mirrors updated,
        ASSIGNED status); the remaining tasks are returned for the host
        path to handle (rejections need its per-filter explanations).
        """
        from ..scheduler.scheduler import SchedulingDecision
        from .kernel import feasibility_jit

        t = tasks[0]
        if not self._supported(t):
            return tasks
        c = t.spec.container
        if c is not None and (c.mounts or getattr(c, "volumes", None)):
            return tasks   # volume selection is host-path logic
        if any(tk.desired_state > TaskState.COMPLETE for tk in tasks):
            # batched mirror counting assumes every admitted task counts
            # toward active totals (nodeinfo.py:132 addTask guard) —
            # shutdown-marked stragglers take the host path
            return tasks
        if not self.breaker.allow_device():
            # breaker open: host loop validates; counted like
            # dispatch_group so route breakdowns stay honest
            self._count("groups_breaker_to_host")
            return tasks
        if self._below_break_even(len(tasks)):
            self.breaker.abort_probe()
            return tasks   # below device break-even: host loop
        import time as _time
        _plan_t0 = _time.perf_counter()
        with tracer.span("plan.build_inputs", "plan", tasks=len(tasks)):
            built = self._build_device_inputs(sched, t, len(tasks))
        if built is None or built[1] == 0:
            self.breaker.abort_probe()
            return tasks
        (infos, n, nb, valid, cpu, mem, total, nodes_in, group_in, L,
         hier, cpu_d, mem_d, gen_wanted, port_limited) = built
        if gen_wanted or port_limited:
            self.breaker.abort_probe()
            return tasks   # per-task claim bookkeeping: host path

        import jax as _jax
        try:
            with tracer.span("plan.feasibility", "plan", tasks=len(tasks)):
                _feas_bucket = "feas_" + _bucket_label(nodes_in, group_in,
                                                       1, ())
                _cache_before = _jit_cache_size(feasibility_jit)
                _devtel.note_h2d("group_inputs",
                                 _devtel.tree_nbytes((nodes_in, group_in)))
                _feas_t0 = _time.perf_counter()
                _fetched = _jax.device_get(
                    feasibility_jit(nodes_in, group_in))
                _feas_dt = _time.perf_counter() - _feas_t0
                mask, cap, _ = _fetched
                _devtel.note_d2h("feasibility",
                                 _devtel.tree_nbytes(_fetched))
                _comp = _observe_compile(feasibility_jit, _feas_bucket,
                                         _cache_before, _feas_dt)
                _devtel.note_kernel(_feas_bucket, "feasibility",
                                    dispatch_s=_feas_dt, compile_s=_comp,
                                    task_rows=len(tasks), node_rows=nb)
        except Exception:
            log.exception("device feasibility failed; host validates")
            self._count("groups_device_error")
            self.breaker.record_failure()
            self._cache = None
            return tasks
        self.breaker.record_success()
        col = {info.node.id: i for i, info in enumerate(infos)}

        items = []      # (task_id, task) admitted
        slots = []      # node column per admitted task
        remaining = []
        used = np.zeros(nb, np.int32)
        for task in tasks:
            i = col.get(task.node_id)
            if i is None or not mask[i] or used[i] >= cap[i]:
                remaining.append(task)
                continue
            used[i] += 1
            items.append((task.id, task))
            slots.append(i)
        self._observe_plan(_time.perf_counter() - _plan_t0)
        if not items:
            return remaining

        with tracer.span("plan.apply", "plan", tasks=len(items)):
            self._apply_assignments(
                sched, t, items, slots, infos, decisions, cpu_d, mem_d,
                used, cpu, mem, total,
                message="scheduler confirmed task can run on preassigned "
                        "node")
        self._count("tasks_planned", len(items))
        return remaining

    def discard_inflight(self) -> None:
        """Drop dispatched-but-unfetched plans (aborted tick): their
        results are never applied, and the column cache is invalidated
        since mirrors may no longer match what was densified.  A
        discarded plan may have been the breaker's half-open probe —
        release the slot (no outcome observed) or the breaker would
        stay wedged in half-open with no path back to the device."""
        self.breaker.abort_probe()
        if self._inflight:
            self._inflight.clear()
            self._cache = None
        if self._fused_active is not None:
            self.abort_fused_run(self._fused_active)
            self._cache = None

    def fetch_group(self, handle: _InFlightPlan) -> bool:
        """Pipeline stage 2: block on the dispatched plan's D2H, then
        apply it to the scheduler mirrors / decision draft.  Returns True
        when the device handled the group (``task_group`` retains any
        unplaceable leftovers), False when the plan spilled and the
        caller must re-run the group through the host oracle (counters
        and cache invalidation already applied, as in the serial path).

        Handles must be fetched oldest-first (FIFO) — each plan's apply
        feeds the next plan's input columns.
        """
        import time as _time

        if not self._inflight or self._inflight[0] is not handle:
            raise RuntimeError("fetch_group out of dispatch order")
        self._inflight.popleft()
        sched, t = handle.sched, handle.t
        task_group, decisions = handle.task_group, handle.decisions
        _plan_t0 = handle.plan_t0
        (infos, n, nb, valid, cpu, mem, total, nodes_in, group_in, L,
         hier, cpu_d, mem_d, gen_wanted, port_limited) = handle.built
        k = len(task_group)
        # one round-trip for all outputs: D2H latency dominates over
        # tunneled links, so never fetch twice
        _d2h_t0 = _time.perf_counter()
        try:
            with tracer.span("plan.d2h", "plan"):
                x, fail_counts, spill = fetch_plan(handle.arrays)
        except Exception:
            # fetch failure: the plan is lost but the group is not — it
            # re-runs through the host oracle (return False), and the
            # breaker counts the device failure
            log.exception("device fetch failed; group routed to host")
            handle.arrays = None
            self._observe_plan(_time.perf_counter() - _plan_t0)
            self._count("groups_device_error")
            self.breaker.record_failure()
            self._cache = None
            return False
        handle.arrays = None
        # the d2h wait IS the device plane's busy window: the host is
        # stalled on the accelerator, which is what saturation means here
        _d2h_dt = _time.perf_counter() - _d2h_t0
        _planes.plane(_planes.DEVICE).note_busy(_d2h_dt)
        if handle.bucket:
            # the fetch half of this plan's kernel-ledger row (bytes
            # were counted inside the fetch_plan seam)
            _devtel.note_kernel(handle.bucket, handle.route,
                                d2h_s=_d2h_dt)
        self.breaker.record_success()
        self._note_inflight(_time.perf_counter() - _plan_t0)
        if bool(spill):
            # a spread branch saturated: the host oracle's convergence
            # loop redistributes differently than the water-fill in that
            # regime (see kernel.py) — keep exact reference parity by
            # letting the host place this group
            self._observe_plan(_time.perf_counter() - _plan_t0)
            self._count("groups_spill_to_host")
            self._cache = None
            return False
        self.last_explanation = self._explain(fail_counts)
        self._observe_plan(_time.perf_counter() - _plan_t0)

        # ---- apply: expand per-node counts into per-task decisions
        from ..scheduler.scheduler import SchedulingDecision
        slots = np.repeat(np.arange(x.shape[0]), x).tolist()
        items = list(task_group.items())
        ts_now = now()
        shared_status = TaskStatus(
            state=TaskState.ASSIGNED, timestamp=ts_now,
            message="scheduler assigned task to node")
        all_tasks = sched.all_tasks
        placed = 0
        # batched per-node counting below assumes every placed task counts
        # toward active-task totals, which holds only for desired_state <=
        # COMPLETE (reference: nodeinfo.go:132 addTask guard) — tasks
        # already marked for shutdown take the per-task path
        simple = (not gen_wanted and not port_limited
                  and not any(tk.desired_state > TaskState.COMPLETE
                              for _, tk in items))
        if simple:
            # batched mirror update: per-task dict entries, per-*node*
            # counter/resource arithmetic (NodeInfo.add_task is O(1) but
            # its Python cost dominates large groups when run per task)
            placed = min(len(items), len(slots))
            counts = np.asarray(x)
            with tracer.span("plan.apply", "plan", tasks=placed):
                self._apply_assignments(sched, t, items[:placed],
                                        slots[:placed], infos, decisions,
                                        cpu_d, mem_d, counts, cpu, mem,
                                        total)
            if placed == len(task_group):
                task_group.clear()
            else:
                for task_id, _ in items[:placed]:
                    del task_group[task_id]
        else:
            # generic resources / host ports need per-task claim bookkeeping
            self._cache = None   # add_task mutates behind the columns
            with tracer.span("plan.apply", "plan", tasks=len(slots)):
                for (task_id, task), node_i in zip(items, slots):
                    info = infos[node_i]
                    new_t = _fast_assign(task, info.id, shared_status)
                    all_tasks[task_id] = new_t
                    info.add_task(new_t)
                    decisions[task_id] = SchedulingDecision(task, new_t)
                    del task_group[task_id]
                    placed += 1

        self._count("groups_planned")
        self._count("tasks_planned", placed)
        return True

    # --------------------------------------------------- victim selection

    def select_victims(self, cand, cpu_d: int, mem_d: int, gen_d: int,
                       n_picks: int, budget: int):
        """Device preemption: the victims×nodes selection kernel
        (ops/preempt.py), byte-identical to the host oracle — including
        the single-kind generic-resource column (``gen_d``; 0 = none).
        Routed through the SAME breaker seam as planning: an open
        breaker or any device failure returns None and the scheduler's
        supervisor runs the host oracle instead — selection never fails
        a tick."""
        import time as _time
        from . import preempt as _preempt
        if not self.breaker.allow_device():
            self._count("preempt_breaker_to_host")
            return None
        try:
            before = _jit_cache_size(_preempt.select_victims_jit)
            t0 = _time.perf_counter()
            with tracer.span("plan.preempt", "plan", picks=n_picks):
                picks, bucket, fn = _preempt.plan_victims(
                    cand, cpu_d, mem_d, gen_d, n_picks, budget)
            dt = _time.perf_counter() - t0
            comp = _observe_compile(fn, bucket, before, dt)
            _devtel.note_kernel(bucket, "preempt", dispatch_s=dt,
                                compile_s=comp, task_rows=n_picks,
                                node_rows=int(cand.ok.shape[0]))
        except Exception:
            log.exception("device victim selection failed; host oracle")
            self._count("preempt_device_error")
            self.breaker.record_failure()
            return None
        self.breaker.record_success()
        return picks

    # -------------------------------------------- gang feasibility check

    def gang_feasible(self, sched, t: Task, k: int) -> Optional[bool]:
        """Group-level all-members-feasible verdict for a gang member
        group (ops/kernel.py ``gang_fit``): True/False when a verdict
        was computed, None when no verdict is available (static bucket
        overflow) and the caller should decide by placement attempt +
        rollback instead.  Device behind the planner breaker with the
        bit-equal numpy host oracle (scheduler/gang.py) serving
        demotions — a breaker flip never changes an admission verdict.
        """
        built = self._build_device_inputs(sched, t, k)
        if built is None:
            return None
        (infos, n, nb, valid, cpu, mem, total, nodes_in, group_in,
         L, hier, cpu_d, mem_d, gen_wanted, port_limited) = built
        if n == 0:
            return False
        bucket = _bucket_label(nodes_in, group_in, L, hier) + "_gf"
        return self._gang_fit_one(nodes_in, group_in, bucket)

    def _gang_fit_one(self, nodes_in, group_in, bucket: str) -> bool:
        """One gang_fit verdict: device kernel behind the breaker, the
        numpy host oracle on open breaker or device failure."""
        import time as _time
        if self.breaker.allow_device():
            try:
                before = _jit_cache_size(gang_fit_jit)
                _devtel.note_h2d("gang_inputs",
                                 _devtel.tree_nbytes((nodes_in, group_in)))
                t0 = _time.perf_counter()
                with tracer.span("plan.gang_fit", "plan",
                                 k=int(group_in.k)):
                    fit, _fc = gang_fit_jit(nodes_in, group_in)
                    fit = bool(fit)
                dt = _time.perf_counter() - t0
                comp = _observe_compile(gang_fit_jit, bucket, before, dt)
                _devtel.note_kernel(bucket, "gang", dispatch_s=dt,
                                    compile_s=comp,
                                    task_rows=int(group_in.k),
                                    node_rows=nodes_in.valid.shape[0])
            except Exception:
                log.exception("device gang_fit failed; host oracle")
                self._count("gang_device_error")
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
                self._count("gang_fit_device")
                return fit
        from ..scheduler import gang as gang_mod
        self._count("gang_fit_host")
        fit, _fc = gang_mod.gang_fit_host(nodes_in, group_in)
        return fit

    def gang_feasible_many(self, sched, wants) -> list:
        """Fused gang route: verdicts for ``wants`` = [(t, k), ...].
        Same-signature groups (identical bucket label, same quota-mask
        presence) stack on a leading gang axis and judge in ONE
        ``gang_fit_fused_jit`` call (bucket suffix ``_gfF``);
        singletons and breaker demotions take the per-group route.
        Returns [Optional[bool]] aligned with ``wants``."""
        import time as _time
        results: list = [None] * len(wants)
        by_bucket: Dict[str, list] = {}
        for i, (t, k) in enumerate(wants):
            built = self._build_device_inputs(sched, t, k)
            if built is None:
                continue
            (infos, n, nb, valid, cpu, mem, total, nodes_in, group_in,
             L, hier, cpu_d, mem_d, gen_wanted, port_limited) = built
            if n == 0:
                results[i] = False
                continue
            label = _bucket_label(nodes_in, group_in, L, hier)
            by_bucket.setdefault(label, []).append(
                (i, nodes_in, group_in))
        for label, rows in by_bucket.items():
            if len(rows) < 2 or not self.breaker.allow_device():
                for i, nodes_in, group_in in rows:
                    results[i] = self._gang_fit_one(
                        nodes_in, group_in, label + "_gf")
                continue
            try:
                stacked_nodes = NodeInputs(*[
                    None if f == "quota_ok"
                    and rows[0][1].quota_ok is None
                    else np.stack([getattr(r[1], f) for r in rows])
                    for f in NodeInputs._fields])
                stacked_groups = GroupInputs(*[
                    np.stack([getattr(r[2], f) for r in rows])
                    for f in GroupInputs._fields])
                before = _jit_cache_size(gang_fit_fused_jit)
                _devtel.note_h2d("gang_inputs", _devtel.tree_nbytes(
                    (stacked_nodes, stacked_groups)))
                t0 = _time.perf_counter()
                with tracer.span("plan.gang_fit_fused", "plan",
                                 gangs=len(rows)):
                    fits, _fcs = gang_fit_fused_jit(stacked_nodes,
                                                    stacked_groups)
                    fits = [bool(f) for f in fits]
                dt = _time.perf_counter() - t0
                comp = _observe_compile(gang_fit_fused_jit,
                                        label + "_gfF", before, dt)
                _devtel.note_kernel(label + "_gfF", "gang_fused",
                                    dispatch_s=dt, compile_s=comp,
                                    groups=len(rows))
            except Exception:
                log.exception("fused gang_fit failed; host oracle")
                self._count("gang_device_error")
                self.breaker.record_failure()
                from ..scheduler import gang as gang_mod
                for i, nodes_in, group_in in rows:
                    self._count("gang_fit_host")
                    fit, _fc = gang_mod.gang_fit_host(nodes_in,
                                                      group_in)
                    results[i] = fit
            else:
                self.breaker.record_success()
                self._count("gang_fit_fused", len(rows))
                for (i, _n, _g), fit in zip(rows, fits):
                    results[i] = fit
        return results

    # ----------------------------------------------- fused many-service

    def probe_fused_run(self, sched, glist, start: int) -> list:
        """Maximal run of consecutive fusable groups from ``glist``
        [start:], as parsed GroupSpecs.  Empty when fusion is off, the
        breaker is not closed (per-group routing owns probe accounting),
        or the first group is not fusable — the scheduler then takes the
        per-group path for exactly the groups a per-group tick would
        route the same way."""
        if not self.fused_enabled or self._fused_dead:
            return []
        if self.breaker.state != BREAKER_CLOSED:
            return []
        specs = []
        for group in glist[start:]:
            if self._below_break_even(len(group)):
                break   # below device break-even: host path
            spec = fusedbatch.probe_group(self, sched, group)
            if spec is None:
                break
            specs.append(spec)
        return specs

    def dispatch_fused_run(self, sched, specs):
        """Densify + dispatch one fused run (>= 2 groups).  Returns a
        FusedRun handle or None when the batch cannot be built or the
        first dispatch fails — the caller falls back group-by-group
        (identical placements; no mirror state was touched here)."""
        try:
            run = fusedbatch.build_run(self, sched, specs)
        except Exception:
            log.exception("fused batch build failed; per-group path")
            self._fused_dead = True
            return None
        if run is None:
            self._count("fused_overflows")
            return None
        try:
            with fusedbatch.x64():
                run.shared, run.carry = self._prepare_fused(run.shared,
                                                            run.carry)
            self._dispatch_fused_chunks(run)
        except Exception:
            log.exception("fused dispatch failed; per-group path")
            self._count("groups_device_error")
            self.breaker.record_failure()
            self._fused_dead = True
            return None
        if run.dispatch_dead and run.next_dispatch == 0:
            return None
        self._fused_active = run
        return run

    def _prepare_fused(self, shared, carry):
        """Device placement of a run's node state (called under the x64
        guard): mesh plan fns shard it with NamedShardings; the
        single-device path is a plain transfer.  Either way the arrays
        stay device-resident across every chunk of the run.

        With the streaming plane fresh (no mirror mutation since the
        resident device sync), the five node-state columns are ALREADY
        on device — the run seeds its FusedShared/FusedCarry from the
        resident arrays and skips their H2D transfer entirely.  Values
        equal the host mirrors bit-for-bit (the donated scatter applies
        the same per-row updates), so placements cannot change."""
        fn = self._fused_fn
        if fn is not None and hasattr(fn, "prepare_fused"):
            # mesh path: when the streaming plane's device tier is
            # sharded over THIS plan fn's mesh, the run seeds node state
            # from the resident shards — zero cross-device reshuffle,
            # only the small per-run extras transfer (sharded by the
            # plan fn).  Same identity guard as the single-device path.
            st = self._streaming
            if st is not None and (not self.streaming_enabled
                                   or shared.valid is not st.valid):
                st = None
            dev = st.device_carry() if st is not None else None
            if dev is not None and getattr(st, "_mesh_active", False) \
                    and st.mesh is getattr(fn, "mesh", None):
                self._count("streaming_device_carries")
                _devtel.note_bytes_avoided(_devtel.tree_nbytes(
                    (shared.valid, shared.ready, carry.total, carry.cpu,
                     carry.mem)))
                return fn.prepare_fused(shared, carry, resident=dev)
            return fn.prepare_fused(shared, carry)
        import jax.numpy as jnp
        from .kernel import FusedCarry, FusedShared
        # identity guard, like every other streaming fast path: the
        # run's shared.valid IS the resident host column iff build_run
        # densified from the resident state — a run built from foreign
        # columns (hatch off, tracker-less sched) must never be seeded
        # from another scheduler's resident device arrays
        st = self._streaming
        if st is not None and (not self.streaming_enabled
                               or shared.valid is not st.valid):
            st = None
        dev = st.device_carry() if st is not None else None
        if dev is not None and getattr(st, "_mesh_active", False):
            # resident tier sharded but no mesh plan fn to consume it:
            # the single-device fused path re-uploads from the host
            # mirror rather than gathering shards through the host
            dev = None
        if dev is not None:
            d_valid, d_ready, d_cpu, d_mem, d_total = dev
            self._count("streaming_device_carries")
            # the resident carry spares this run the five node-state
            # column uploads; only the small per-run extras transfer
            _devtel.note_bytes_avoided(_devtel.tree_nbytes(
                (shared.valid, shared.ready, carry.total, carry.cpu,
                 carry.mem)))
            _devtel.note_h2d("cold_build", _devtel.tree_nbytes(
                (shared.os_hash, shared.arch_hash, shared.svc0,
                 carry.svc_acc)))
            return (FusedShared(valid=d_valid, ready=d_ready,
                                os_hash=jnp.asarray(shared.os_hash),
                                arch_hash=jnp.asarray(shared.arch_hash),
                                svc0=jnp.asarray(shared.svc0)),
                    FusedCarry(total=d_total, cpu=d_cpu, mem=d_mem,
                               svc_acc=jnp.asarray(carry.svc_acc)))
        _devtel.note_h2d("cold_build",
                         _devtel.tree_nbytes((tuple(shared),
                                              tuple(carry))))
        return (FusedShared(*(jnp.asarray(a) for a in shared)),
                FusedCarry(*(jnp.asarray(a) for a in carry)))

    def _fused_jit_probe(self):
        """The underlying jit callable whose cache growth is observed
        for compile accounting (None when the plan fn hides it)."""
        if self._fused_fn is None:
            return plan_fused_jit
        from ..parallel.sharded import plan_fused_sharded
        return plan_fused_sharded

    def _strategy_jit_probe(self):
        """Strategy-kernel twin of ``_fused_jit_probe``."""
        if not hasattr(self._plan_fn, "strategy"):
            return plan_strategy_jit
        from ..parallel.sharded import plan_strategy_sharded
        return plan_strategy_sharded

    def _dispatch_fused_chunks(self, run) -> None:
        """Dispatch chunks until two are in flight (or the run is fully
        dispatched).  Two in flight = the device computes chunk i+1
        while the host fetches/applies/commits chunk i; deeper would
        only hold H2D buffers longer.  A dispatch failure marks the run
        dispatch-dead: already-dispatched chunks still apply, the rest
        of the tick rides the per-group path."""
        import time as _time
        while (not run.dispatch_dead and not run.aborted
               and run.next_dispatch < len(run.chunks)
               and run.next_dispatch - run.next_fetch < 2):
            c = run.chunks[run.next_dispatch]
            bucket = run.bucket_label(c)
            probe = self._fused_jit_probe()
            before = _jit_cache_size(probe)
            _devtel.note_h2d("fused_inputs",
                             _devtel.tree_nbytes((c.groups, c.strat)))
            c.t0 = _time.perf_counter()
            try:
                with tracer.span("plan.dispatch", "plan", tasks=c.tasks,
                                 fused_groups=c.count):
                    with fusedbatch.x64():
                        fn = (self._fused_fn.fused
                              if self._fused_fn is not None
                              else plan_fused_jit)
                        if c.strat is not None:
                            xs, fcs, spills, carry = fn(
                                run.shared, c.groups, run.carry, run.L,
                                c.strat)
                        else:
                            xs, fcs, spills, carry = fn(
                                run.shared, c.groups, run.carry, run.L)
            except Exception:
                log.exception("fused chunk dispatch failed; remaining "
                              "groups ride the per-group path")
                self._count("groups_device_error")
                self.breaker.record_failure()
                self._fused_dead = True
                run.dispatch_dead = True
                return
            dt = _time.perf_counter() - c.t0
            comp = _observe_compile(probe, bucket, before, dt)
            _devtel.note_kernel(bucket, "fused", dispatch_s=dt,
                                compile_s=comp, groups=c.count,
                                task_rows=c.tasks)
            c.arrays = (xs, fcs, spills)
            c.groups = None   # release the np staging buffers
            c.strat = None
            run.carry = carry   # device-resident; never fetched
            run.next_dispatch += 1
            self._count("fused_chunks")

    def fetch_fused_chunk(self, run):
        """Block on the next chunk's D2H and prime the following
        dispatch.  Returns (x [G, N], fail_counts [G, 7], spill [G],
        start, count) as numpy, or None when the run is exhausted or
        died (remaining groups take the per-group path)."""
        import time as _time
        if run.aborted or run.next_fetch >= run.next_dispatch:
            return None
        c = run.chunks[run.next_fetch]
        _d2h_t0 = _time.perf_counter()
        try:
            with tracer.span("plan.d2h", "plan"):
                xs, fcs, spills = fetch_plan(c.arrays)
        except Exception:
            log.exception("fused fetch failed; remaining groups ride "
                          "the per-group path")
            self._count("groups_device_error")
            self.breaker.record_failure()
            self._fused_dead = True
            self._cache = None
            self.abort_fused_run(run)
            return None
        c.arrays = None
        run.next_fetch += 1
        self.breaker.record_success()
        end = _time.perf_counter()
        _planes.plane(_planes.DEVICE).note_busy(end - _d2h_t0)
        _devtel.note_kernel(run.bucket_label(c), "fused",
                            d2h_s=end - _d2h_t0)
        # chunk windows overlap (two dispatches in flight): charge
        # plan_seconds only the wall time this chunk ADDED beyond the
        # previous fetch, or summed plan_s would exceed the tick wall
        self._observe_plan(end - max(c.t0, run.last_fetch_end))
        run.last_fetch_end = end
        self._note_inflight(end - c.t0)
        self._dispatch_fused_chunks(run)   # keep the pipeline primed
        return (np.asarray(xs), np.asarray(fcs), np.asarray(spills),
                c.start, c.count)

    def apply_fused_group(self, run, gi: int, x_row, fail_row,
                          decisions) -> int:
        """Apply one fused group's placements to the scheduler mirrors /
        decision draft — the same simple-path apply as ``fetch_group``
        (fusability guarantees no generics/ports/shutdown stragglers).
        Returns the number of tasks placed; the group dict retains any
        unplaceable leftovers and ``last_explanation`` is set for the
        caller's no-suitable-node pass."""
        spec = run.specs[gi]
        sched, t, task_group = run.sched, spec.t, spec.group
        infos, n, nb, valid, ready, cpu, mem, total = run.cols
        self.last_explanation = self._explain(fail_row)
        x = np.asarray(x_row)
        slots = np.repeat(np.arange(x.shape[0]), x).tolist()
        items = list(task_group.items())
        placed = min(len(items), len(slots))
        with tracer.span("plan.apply", "plan", tasks=placed):
            self._apply_assignments(sched, t, items[:placed],
                                    slots[:placed], infos, decisions,
                                    spec.cpu_d, spec.mem_d, x, cpu, mem,
                                    total)
        if placed == len(task_group):
            task_group.clear()
        else:
            for task_id, _ in items[:placed]:
                del task_group[task_id]
        run.applied = gi + 1
        self._count("groups_fused")
        if spec.sid:
            # non-spread group served by the fused device path: same
            # per-strategy route accounting as the per-group kernel
            strategy_mod.count_group(spec.sname, "device")
        self._count("tasks_planned", placed)
        return placed

    def note_fused_spill(self, run) -> None:
        """A fused group's spread branches saturated: the group goes to
        the host oracle for exact reference parity (same flag as the
        per-group path), which invalidates the column cache and aborts
        the rest of the run — later groups were planned against this
        group's device placement, which no longer happens."""
        self._count("groups_spill_to_host")
        self._cache = None
        self.abort_fused_run(run)

    def abort_fused_run(self, run) -> None:
        """Release a fused run (normal completion or abort): drop
        undispatched staging buffers and unfetched device arrays."""
        run.aborted = True
        for c in run.chunks:
            c.arrays = None
            c.groups = None
            c.strat = None
        run.carry = None
        run.shared = None
        if self._fused_active is run:
            self._fused_active = None
