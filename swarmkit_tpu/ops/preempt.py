"""Device victim selection: batched preemption as a victims×nodes
score-matrix program.

The host oracle (scheduler/preempt.py ``select_victims_host``) is a
sequential greedy: per pick, every node's cheapest victim prefix is
scored and the (cost, victim-count, node-index)-minimal node wins.
That per-node prefix computation is a pure function of the candidate
columns — so it runs as ONE vmap over the node axis (cumulative sums
down the victim axis, one comparison ladder), and the sequential picks
become a ``lax.scan`` whose carry (used-victim mask, per-node freed
surplus, remaining victim budget, stop flag) IS the greedy's mutable
state.  Every quantity is integer (resources i64 under the scoped
``enable_x64`` guard shared with the fused planner, costs/counts i32),
so the outputs are byte-identical to the oracle — asserted by the
differential fuzz in tests/test_preemption.py across node/victim/pick
buckets and seeds.

Shape discipline follows the planner's bucket ladder: nodes pad to the
shared ``n_bucket`` pow2 ladder, victim slots to ``V_BUCKETS``
({4, 16, 64}, scheduler/preempt.py), picks to a pow2 bucket — one jit
signature per (NB, VB, PB) triple, counted by the planner's compile
observer like every other kernel.  Routing/fallback lives in
ops/planner.py ``TPUPlanner.select_victims`` (PlannerBreaker-gated,
any device failure degrades to the host oracle).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import devicetelemetry as _devtel
from ..scheduler.preempt import CandidateSet
from . import fusedbatch

#: packed tie-break key layout: cost << 27 | nvict << 20 | node index —
#: cost < 2^27 (64 victims x (PRIO_CLAMP+1)), nvict < 2^7, index < 2^20
_IDX_BITS = 20
_NV_BITS = 7


def pick_bucket(n: int) -> int:
    """Pow2 pick-slot bucket (>= 1)."""
    return fusedbatch.pow2_bucket(max(n, 1))


def _node_prefix(ok_j, free_c, free_m, free_g, ex_c, ex_m, ex_g,
                 live_col, vcpu_col, vmem_col, vgen_col, w_col,
                 cpu_d, mem_d, gen_d):
    """One node's cheapest victim prefix: (feasible, m, cost, nvict).
    ``m`` is the smallest prefix length whose unused victims free enough
    cpu AND memory AND (single-kind discrete) generic units on top of
    the node's (possibly negative) free pools.  vmapped over the node
    axis by ``select_victims_jit``."""
    zero64 = jnp.zeros((1,), vcpu_col.dtype)
    zero32 = jnp.zeros((1,), jnp.int32)
    cum_c = jnp.concatenate(
        [zero64, jnp.cumsum(jnp.where(live_col, vcpu_col, 0))])
    cum_m = jnp.concatenate(
        [zero64, jnp.cumsum(jnp.where(live_col, vmem_col, 0))])
    cum_g = jnp.concatenate(
        [zero64, jnp.cumsum(jnp.where(live_col, vgen_col, 0))])
    cum_w = jnp.concatenate(
        [zero32, jnp.cumsum(jnp.where(live_col, w_col, 0))])
    cum_n = jnp.concatenate(
        [zero32, jnp.cumsum(live_col.astype(jnp.int32))])
    # fits[m] is monotone in m (freed resources are non-negative), so
    # argmax finds the FIRST satisfying prefix — the oracle's break
    fits = ((free_c + ex_c + cum_c >= cpu_d)
            & (free_m + ex_m + cum_m >= mem_d)
            & (free_g + ex_g + cum_g >= gen_d))
    m = jnp.argmax(fits).astype(jnp.int32)
    feasible = ok_j & jnp.any(fits)
    cost = jnp.take(cum_w, m)
    nvict = jnp.take(cum_n, m)
    return feasible, m, cost, nvict


@functools.partial(jax.jit, static_argnames=("picks",))
def select_victims_jit(ok, free_cpu, free_mem, free_gen, vvalid, vprio,
                       vcpu, vmem, vgen, cpu_d, mem_d, gen_d, n_picks,
                       budget, picks: int):
    """Sequential greedy picks as a scan; returns (node i32[picks],
    m i32[picks]) with -1/0 rows for inactive (stopped or > n_picks)
    picks.  See module docstring for the exactness contract."""
    V, N = vvalid.shape
    weights = (vprio + 1).astype(jnp.int32)    # clamped host-side
    slot_idx = jnp.arange(V, dtype=jnp.int32)
    node_idx = jnp.arange(N, dtype=jnp.int64)
    maxkey = jnp.iinfo(jnp.int64).max

    prefix = jax.vmap(_node_prefix,
                      in_axes=(0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 1,
                               None, None, None))

    def step(state, p):
        used, ex_c, ex_m, ex_g, budget_rem, stopped = state
        live = vvalid & ~used
        feasible, m, cost, nvict = prefix(
            ok, free_cpu, free_mem, free_gen, ex_c, ex_m, ex_g, live,
            vcpu, vmem, vgen, weights, cpu_d, mem_d, gen_d)
        key = ((cost.astype(jnp.int64) << (_IDX_BITS + _NV_BITS))
               | (nvict.astype(jnp.int64) << _IDX_BITS) | node_idx)
        key = jnp.where(feasible, key, maxkey)
        j = jnp.argmin(key).astype(jnp.int32)
        any_f = jnp.take(feasible, j)
        m_j = jnp.take(m, j)
        nv_j = jnp.take(nvict, j)
        over = nv_j > budget_rem
        active = (p < n_picks) & ~stopped
        do = active & any_f & ~over
        sel = jnp.take(live, j, axis=1) & (slot_idx < m_j) & do
        freed_c = jnp.sum(jnp.where(sel, jnp.take(vcpu, j, axis=1), 0))
        freed_m = jnp.sum(jnp.where(sel, jnp.take(vmem, j, axis=1), 0))
        freed_g = jnp.sum(jnp.where(sel, jnp.take(vgen, j, axis=1), 0))
        used = used.at[:, j].set(used[:, j] | sel)
        ex_c = ex_c.at[j].add(jnp.where(do, freed_c - cpu_d, 0))
        ex_m = ex_m.at[j].add(jnp.where(do, freed_m - mem_d, 0))
        ex_g = ex_g.at[j].add(jnp.where(do, freed_g - gen_d, 0))
        budget_rem = budget_rem - jnp.where(do, nv_j, 0)
        stopped = stopped | (active & (~any_f | over))
        out_node = jnp.where(do, j, -1)
        out_m = jnp.where(do, m_j, 0)
        return (used, ex_c, ex_m, ex_g, budget_rem, stopped), \
            (out_node, out_m)

    state = (jnp.zeros((V, N), bool),
             jnp.zeros((N,), free_cpu.dtype),
             jnp.zeros((N,), free_mem.dtype),
             jnp.zeros((N,), free_gen.dtype),
             jnp.asarray(budget, jnp.int32),
             jnp.zeros((), bool))
    _, (nodes, ms) = jax.lax.scan(
        step, state, jnp.arange(picks, dtype=jnp.int32))
    return nodes, ms


def plan_victims(cand: CandidateSet, cpu_d: int, mem_d: int, gen_d: int,
                 n_picks: int, budget: int
                 ) -> Tuple[List[Tuple[int, int]], str, object]:
    """Pad the host-built candidate arrays to their static buckets,
    dispatch the kernel, fetch and unpad.  Returns (picks, bucket label,
    jit fn) — the label and fn feed the planner's compile observer.
    Raises on any device failure (the caller owns breaker/fallback)."""
    V, n = cand.vvalid.shape
    nb = fusedbatch.n_bucket(max(n, 1))
    # the caller caps n_picks (supervisor: min(group size, budget)) so
    # host and device run the SAME number of pick iterations
    pb = pick_bucket(n_picks)
    ok = np.zeros(nb, bool)
    ok[:n] = cand.ok
    free_cpu = np.zeros(nb, np.int64)
    free_cpu[:n] = cand.free_cpu
    free_mem = np.zeros(nb, np.int64)
    free_mem[:n] = cand.free_mem
    free_gen = np.zeros(nb, np.int64)
    free_gen[:n] = cand.free_gen
    vvalid = np.zeros((V, nb), bool)
    vvalid[:, :n] = cand.vvalid
    vprio = np.zeros((V, nb), np.int32)
    vprio[:, :n] = cand.vprio
    vcpu = np.zeros((V, nb), np.int64)
    vcpu[:, :n] = cand.vcpu
    vmem = np.zeros((V, nb), np.int64)
    vmem[:, :n] = cand.vmem
    vgen = np.zeros((V, nb), np.int64)
    vgen[:, :n] = cand.vgen
    label = f"preempt_nb{nb}_v{V}_p{pb}"
    _devtel.note_h2d("preempt_inputs", _devtel.tree_nbytes(
        (ok, free_cpu, free_mem, free_gen, vvalid, vprio, vcpu, vmem,
         vgen)))
    with fusedbatch.x64():
        nodes, ms = jax.device_get(select_victims_jit(
            ok, free_cpu, free_mem, free_gen, vvalid, vprio, vcpu, vmem,
            vgen, np.int64(cpu_d), np.int64(mem_d), np.int64(gen_d),
            np.int32(n_picks), np.int32(budget), pb))
    _devtel.note_d2h("preempt", _devtel.tree_nbytes((nodes, ms)))
    picks: List[Tuple[int, int]] = []
    for j, m in zip(nodes.tolist(), ms.tolist()):
        if j < 0:
            continue
        picks.append((int(j), int(m)))
    return picks, label, select_victims_jit
