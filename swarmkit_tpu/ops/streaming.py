"""Device-resident node state for the streaming scheduler (ISSUE 14).

``TPUPlanner._build_columns`` re-densifies the whole NodeSet mirror into
SoA columns every tick — O(cluster) Python work per tick, even when the
tick's churn touched three nodes.  ``ResidentState`` keeps those columns
(and the per-group column *precursors*: per-service task counts, node
platform hashes, constraint hash columns, spread leaves, failure rows)
alive across ticks and refreshes only the rows the scheduler's
``DeltaTracker`` marked dirty — the hardware-task-scheduler move of
amortizing decision cost across a persistent structure (PAPERS.md: HTS
1907.00271, DaphneSched 2308.01607).

Two tiers of residency:

* **host mirror** — numpy columns updated row-wise from the NodeInfo
  ground truth.  These feed the per-group kernel inputs and the exact
  int64 resource math, so incremental refresh is byte-identical to a
  full rebuild by construction (same per-row formulas, same row order —
  appends match the NodeSet dict's insertion order; removals demand a
  full rebuild because row index is a placement tie-break key).
* **device arrays** — jnp copies of the five node-state columns
  (valid/ready/cpu/mem/total), updated in place by a **donated** scatter
  program (``_scatter_rows_jit``: ``donate_argnums`` lets XLA reuse the
  resident buffers instead of allocating per delta — the pjit/donation
  idiom in SNIPPETS.md [1]/[2]).  The fused planner seeds its
  ``FusedShared``/``FusedCarry`` node columns from them when fresh,
  skipping the per-run H2D of the big columns.  The resident arrays are
  never read back to host mid-program — D2H belongs to the fetch stage
  (swarmlint device-path-purity).

On a planner mesh (``SWARM_PLANNER_MESH``) the device tier is
**node-axis sharded** (parallel/sharded.py): each device owns nb/D
rows, uploads stage per shard (``device_put`` with a NamedSharding
ships each device its own slice), and the dirty-row scatter becomes a
per-shard donated program — rows are bucketed by owning shard
host-side, so a streaming tick moves O(churn) bytes and zero
cross-device traffic, and the fused run seeds sharded
``FusedShared``/``FusedCarry`` columns with no reshuffle.

Fallback matrix (every full rebuild is counted; the escape hatch
``SWARM_STREAMING_PLANNER=0`` turns the whole plane off):

=====================  =======================================
cold start             first refresh ever (counted ``cold``)
leader handoff         tick epoch != resident epoch → resync —
                       a successor must rebuild from its own
                       replicated store before trusting rows
node removal / store   row order shifts → full rebuild
resync
node-bucket overflow   cluster outgrew ``nb`` → rebuild into
                       the next pow2 bucket
tracker divergence     mirror count != resident count (a missed
                       hook) → rebuild, never trust drifted rows
mesh shard-count       planner mesh resized → the resident
change                 shards have the wrong layout; device tier
                       re-uploads (host mirror stays valid)
mesh teardown          planner mesh removed → device tier
                       demotes to single-device residency
=====================  =======================================
"""

from __future__ import annotations

import functools
import logging
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..models.types import NodeAvailability, NodeState
from ..obs import devicetelemetry as _devtel
from ..utils.metrics import registry as _metrics
from . import fusedbatch
from .fusedbatch import SENTINEL, n_bucket, split_hash
from .hashing import str_hash

log = logging.getLogger("tpu-streaming")

_REFRESH_TIMER = _metrics.timer("swarm_streaming_refresh_latency")

#: dirty-row scatter buckets (jit signatures stay bounded); a refresh
#: dirtier than the top bucket re-uploads the columns wholesale
D_BUCKETS = (16, 256, 4096)

#: per-service column cache bound (FIFO eviction — oldest-built goes
#: first; deterministic): steady-state workloads cycle a few dozen
#: services, and an evicted column simply rebuilds on next demand
SVC_CACHE_CAP = 64
CON_CACHE_CAP = 32
LEAF_CACHE_CAP = 16

_UNSET = object()


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def _scatter_rows_jit(valid, ready, cpu, mem, total, idx,
                      u_valid, u_ready, u_cpu, u_mem, u_total):
    """In-place dirty-row update of the resident device columns.  The
    five resident arrays are DONATED: XLA writes the updates into the
    existing buffers instead of allocating a cluster-sized copy per
    delta batch.  Padded index slots carry ``nb`` (out of bounds) and
    drop."""
    kw = dict(mode="drop")
    return (valid.at[idx].set(u_valid, **kw),
            ready.at[idx].set(u_ready, **kw),
            cpu.at[idx].set(u_cpu, **kw),
            mem.at[idx].set(u_mem, **kw),
            total.at[idx].set(u_total, **kw))


def _d_bucket(d: int) -> Optional[int]:
    for b in D_BUCKETS:
        if d <= b:
            return b
    return None


class _ConColumn:
    """One cached constraint-key hash column: per-node value hashes
    (hi/lo int32) plus whether ANY node's value was unknown (the whole
    constraint then disables with the sentinel, matching
    ``fusedbatch.fill_constraints``)."""

    __slots__ = ("hash", "none_count")

    def __init__(self, nb: int):
        self.hash = np.zeros((2, nb), np.int32)
        self.none_count = 0


class ResidentState:
    """Persistent densified node state, refreshed O(churn) per tick."""

    def __init__(self, node_value: Callable, device: bool = True,
                 mesh=None):
        #: planner._node_value — constraint-key lookup per NodeInfo
        self._node_value = node_value
        #: planner mesh (parallel/sharded.py) — when set and the node
        #: bucket divides evenly over it, the device tier lives as
        #: node-axis-sharded arrays with per-shard donated scatters
        self.mesh = mesh
        self._mesh_active = False
        self.infos: Optional[List] = None
        self.row_of: Dict[str, int] = {}
        self.n = 0
        self.nb = 0
        self.valid = self.ready = None
        self.cpu = self.mem = self.total = None
        self.os_hash = self.arch_hash = None
        # platform hashes are maintained LAZILY: workloads without
        # platform requirements never pay the 2x str_hash per row
        self._want_platforms = False
        self.node_ids: List[str] = []
        self.task_dicts: List[dict] = []
        #: rows whose NodeInfo has a (possibly expired) failure record —
        #: mirrors the ``if info.recent_failures`` guard of the per-group
        #: failure loop, so the fill visits the same rows it would
        self.fail_rows: Dict[int, None] = {}
        self.svc_cols: Dict[str, np.ndarray] = {}
        self.con_cols: Dict[str, _ConColumn] = {}
        self.leaf_cols: Dict[str, Tuple[np.ndarray, Dict[str, int],
                                        List[str]]] = {}
        self.epoch = _UNSET
        self._tracker = None
        # device tier
        self.device_enabled = device
        self.dev: Optional[tuple] = None     # (valid, ready, cpu, mem, total)
        self._dev_version = -1
        # rows recomputed by a HOST-ONLY absorb (mid-tick accessors):
        # the device tier has not seen them yet — the next device sync
        # must scatter them too, or it would stamp itself fresh while
        # silently missing those rows' updates
        self._pending_dev_rows: Dict[int, None] = {}
        self.stats = {"colds": 0, "resyncs": 0, "fallbacks": 0,
                      "incremental": 0, "full": 0, "rows": 0,
                      "dirty_frac": 0.0, "device_syncs": 0,
                      "svc_evictions": 0, "bytes_avoided": 0,
                      "shard_syncs": 0}

    # --------------------------------------------------------- mesh tier

    def set_mesh(self, mesh) -> None:
        """(Re)wire the planner mesh.  A layout change while device
        arrays exist — mesh resized ("shard-count") or removed
        ("mesh-teardown") — drops the device tier for a counted
        re-upload on the next sync; the host mirror stays valid, so no
        host rebuild happens."""
        if mesh is self.mesh:
            return
        old, self.mesh = self.mesh, mesh
        if self.dev is None and not self._mesh_active:
            return
        reason = "mesh-teardown" if mesh is None else "shard-count"
        self.stats["resyncs"] += 1
        _metrics.counter(
            f'swarm_streaming_resyncs{{reason="{reason}"}}')
        log.info("resident device tier reset (%s): mesh %s -> %s",
                 reason, old, mesh)
        self.dev = None
        self._mesh_active = False
        self._dev_version = -1

    def _mesh_for(self):
        """The usable mesh for the device tier: set, >1 device, and
        evenly dividing the node bucket (pow2 buckets and mesh sizes
        make that the norm; a non-pow2 mesh demotes to the
        single-device tier)."""
        mesh = self.mesh
        if mesh is None or not self.nb:
            return None
        from ..parallel.sharded import NODE_AXIS
        d = mesh.shape[NODE_AXIS]
        if d <= 1 or self.nb % d:
            return None
        return mesh

    # ------------------------------------------------------------- refresh

    def refresh(self, sched) -> list:
        """Bring the resident columns up to date with the scheduler's
        mirror and sync the device tier; returns the planner cols list
        ``[infos, n, nb, valid, ready, cpu, mem, total]``.  O(dirty)
        when incremental, O(cluster) on the counted fallbacks."""
        import time as _time
        t0 = _time.perf_counter()
        rows = self._absorb(sched, device=True, tick=True)
        _REFRESH_TIMER.observe(_time.perf_counter() - t0)
        if rows is not None and self.n:
            frac = len(rows) / float(self.n)
            self.stats["dirty_frac"] = frac
            _metrics.gauge("swarm_streaming_dirty_frac", frac)
        return self.cols()

    def absorb(self, sched) -> None:
        """Host-only incremental catch-up (mid-tick accessors call this
        before reading cached columns).  Cheap no-op when the tracker
        has nothing pending."""
        self._absorb(sched, device=False)

    def cols(self) -> list:
        return [self.infos, self.n, self.nb, self.valid, self.ready,
                self.cpu, self.mem, self.total]

    def _absorb(self, sched, device: bool,
                tick: bool = False) -> Optional[list]:
        tracker = getattr(sched, "delta", None)
        if tracker is None:
            # no delta feed: behave like the non-streaming planner
            self._rebuild(sched, "no-tracker", count="fallbacks")
            return None
        if self._tracker is not None and tracker is not self._tracker:
            # a different scheduler's mirror: its mutations were never
            # observed here — never trust the resident rows
            self._tracker = tracker
            tracker.drain()
            self._rebuild(sched, "tracker-swap", count="fallbacks")
            self.epoch = getattr(sched, "_tick_epoch", None)
            if device:
                self._device_upload()
            return None
        self._tracker = tracker
        epoch = getattr(sched, "_tick_epoch", None)
        if not tracker.pending and self.infos is not None \
                and epoch == self.epoch:
            if tick:
                self.stats["incremental"] += 1
                self.stats["dirty_frac"] = 0.0
                _metrics.counter(
                    'swarm_streaming_ticks{mode="incremental"}')
            if device:
                self._device_sync([])   # flushes any host-only backlog
            return []
        dirty, added, full_reason = tracker.drain()
        if self.infos is None:
            full_reason = full_reason or "cold"
        if full_reason is not None:
            self._rebuild(sched, full_reason)
            self.epoch = epoch
            if device:
                self._device_upload()
            return None
        if self.epoch is not _UNSET and epoch != self.epoch:
            # leader handoff (or the first fenced tick after an unfenced
            # one): the resident state was built under another reign —
            # rebuild from the replicated store before trusting it
            self._rebuild(sched, "epoch", count="resyncs")
            self.epoch = epoch
            if device:
                self._device_upload()
            return None
        node_set = sched.node_set
        rows: List[int] = []
        for nid in added:
            if nid in self.row_of:
                self._rebuild(sched, "divergence", count="fallbacks")
                self.epoch = epoch
                if device:
                    self._device_upload()
                return None
            info = node_set.nodes.get(nid)
            if info is None or self.n >= self.nb:
                reason = "overflow" if info is not None else "divergence"
                self._rebuild(sched, reason, count="fallbacks")
                self.epoch = epoch
                if device:
                    self._device_upload()
                return None
            i = self.n
            self.n += 1
            self.row_of[nid] = i
            self.infos.append(info)
            self.node_ids.append(nid)
            self.task_dicts.append(info.tasks)
            self.valid[i] = True
            self._recompute_row(i, info, append=True)
            rows.append(i)
        if self.n != len(node_set.nodes):
            self._rebuild(sched, "divergence", count="fallbacks")
            self.epoch = epoch
            if device:
                self._device_upload()
            return None
        for nid in dirty:
            i = self.row_of.get(nid)
            if i is None:
                continue   # marked after removal was already demanded
            info = node_set.nodes.get(nid)
            if info is not self.infos[i]:
                # the NodeInfo OBJECT was swapped (not mutated in
                # place): the resident row mirrors a dead object
                self._rebuild(sched, "divergence", count="fallbacks")
                self.epoch = epoch
                if device:
                    self._device_upload()
                return None
            self._recompute_row(i, info)
            rows.append(i)
        if tick:
            self.stats["incremental"] += 1
            _metrics.counter('swarm_streaming_ticks{mode="incremental"}')
        self.stats["rows"] += len(rows)
        if rows:
            _metrics.counter("swarm_streaming_rows", len(rows))
        if device:
            self._device_sync(rows)
        else:
            # host-only drain: the device tier is now behind for these
            # rows — queue them for the next device sync
            for i in rows:
                self._pending_dev_rows[i] = None
        return rows

    # ------------------------------------------------------------ row math

    def _recompute_row(self, i: int, info, append: bool = False) -> None:
        """One row from the NodeInfo ground truth — the exact per-row
        formulas ``_build_columns`` / ``node_platform_hashes`` apply, so
        an incremental row equals its full-rebuild value bit-for-bit."""
        node = info.node
        self.ready[i] = (
            node.status.state == NodeState.READY
            and node.spec.availability == NodeAvailability.ACTIVE)
        self.cpu[i] = info.available_resources.nano_cpus
        self.mem[i] = info.available_resources.memory_bytes
        self.total[i] = info.active_tasks_count
        if self._want_platforms:
            self._recompute_platform_row(i, info)
        if info.recent_failures:
            self.fail_rows[i] = None
        else:
            self.fail_rows.pop(i, None)
        by_svc = info.active_tasks_count_by_service
        for sid, col in self.svc_cols.items():
            col[i] = by_svc.get(sid, 0)
        for key in list(self.con_cols):
            self._recompute_con_row(key, i, info)
        for desc_key in list(self.leaf_cols):
            self._recompute_leaf_row(desc_key, i, info, append)

    def _recompute_platform_row(self, i: int, info) -> None:
        desc = info.node.description
        if desc and desc.platform:
            from ..scheduler.filters import normalize_arch
            self.os_hash[:, i] = split_hash(str_hash(desc.platform.os))
            self.arch_hash[:, i] = split_hash(
                str_hash(normalize_arch(desc.platform.architecture)))
        else:
            self.os_hash[:, i] = SENTINEL
            self.arch_hash[:, i] = SENTINEL

    def _recompute_con_row(self, key: str, i: int, info) -> None:
        entry = self.con_cols[key]
        v = self._node_value(info, key)
        # real value hashes are split into non-negative halves, so the
        # (-1, -1) sentinel doubles as the per-row "was unknown" flag
        was_none = bool(entry.hash[0, i] == SENTINEL[0]
                        and entry.hash[1, i] == SENTINEL[1])
        if v is None:
            entry.hash[:, i] = SENTINEL
            if not was_none:
                entry.none_count += 1
        else:
            entry.hash[:, i] = split_hash(str_hash(v))
            if was_none:
                entry.none_count -= 1

    def _recompute_leaf_row(self, desc_key: str, i: int, info,
                            append: bool) -> None:
        from ..scheduler.nodeset import _pref_value
        entry = self.leaf_cols.get(desc_key)
        if entry is None:
            return   # already invalidated earlier in this absorb pass
        leaf, ids, values = entry
        v = _pref_value(info, desc_key) or ""
        if append:
            values.append(v)
            leaf[i] = ids.setdefault(v, len(ids))
            return
        if values[i] == v:
            return
        # a value change can renumber OTHER rows (leaf ids are
        # first-appearance ordered in row order, and branch index is a
        # spread tie-break the kernel reads): drop the cached column —
        # it rebuilds lazily, exactly as a full rebuild would number it
        del self.leaf_cols[desc_key]

    # ------------------------------------------------------- full rebuild

    def _rebuild(self, sched, reason: str, count: Optional[str] = None
                 ) -> None:
        if count is None:
            count = ("colds" if reason == "cold"
                     else "resyncs" if reason == "epoch"
                     else "fallbacks")
        self.stats[count] += 1
        self.stats["full"] += 1
        self.stats["dirty_frac"] = 1.0
        _metrics.counter('swarm_streaming_ticks{mode="full"}')
        _metrics.counter(
            f'swarm_streaming_resyncs{{reason="{reason}"}}')
        node_set = sched.node_set
        infos = list(node_set.nodes.values())
        n = len(infos)
        nb = n_bucket(max(n, 1))
        self.infos = infos
        self.n = n
        self.nb = nb
        self.row_of = {info.node.id: i for i, info in enumerate(infos)}
        self.node_ids = [info.node.id for info in infos]
        self.task_dicts = [info.tasks for info in infos]
        self.valid = np.zeros(nb, bool)
        self.valid[:n] = True
        self.ready = np.zeros(nb, bool)
        self.cpu = np.zeros(nb, np.int64)
        self.mem = np.zeros(nb, np.int64)
        self.total = np.zeros(nb, np.int32)
        self.os_hash = np.zeros((2, nb), np.int32)
        self.arch_hash = np.zeros((2, nb), np.int32)
        self.fail_rows = {}
        # column caches rebuild lazily at their new width; a full
        # device upload covers every row, so the host-only backlog dies
        self.svc_cols = {}
        self.con_cols = {}
        self.leaf_cols = {}
        self._pending_dev_rows = {}
        for i, info in enumerate(infos):
            self._recompute_row(i, info)
        _devtel.set_watermark("host_mirror", _devtel.tree_nbytes(
            (self.valid, self.ready, self.cpu, self.mem, self.total,
             self.os_hash, self.arch_hash)))

    # -------------------------------------------------- cached precursors

    def svc_tasks_col(self, sched, service_id: str) -> np.ndarray:
        """Per-service active-task column (read-only to callers)."""
        self.absorb(sched)
        col = self.svc_cols.get(service_id)
        if col is None:
            if len(self.svc_cols) >= SVC_CACHE_CAP:
                self.svc_cols.pop(next(iter(self.svc_cols)))
                self.stats["svc_evictions"] += 1
            col = np.zeros(self.nb, np.int32)
            for i, info in enumerate(self.infos):
                c = info.active_tasks_count_by_service.get(service_id, 0)
                if c:
                    col[i] = c
            self.svc_cols[service_id] = col
        return col

    def fill_failures(self, failures: np.ndarray, ts: float, t) -> None:
        """Failure down-weights for one group (rows with failure
        records only — the same rows the O(N) guard loop would visit)."""
        infos = self.infos
        for i in self.fail_rows:
            failures[i] = infos[i].count_recent_failures(ts, t)

    def platform_hashes(self) -> Tuple[np.ndarray, np.ndarray]:
        """Resident node platform hash columns; built in full on first
        demand (a platform-requiring group appeared), row-maintained
        from then on."""
        if not self._want_platforms:
            self._want_platforms = True
            for i, info in enumerate(self.infos):
                self._recompute_platform_row(i, info)
        return self.os_hash, self.arch_hash

    def fill_constraints(self, sched, constraints, con_hash, con_op,
                         con_exp) -> None:
        """Streaming twin of ``fusedbatch.fill_constraints``: per-key
        node-value hash columns are resident and refreshed per dirty
        row, so a group build is a vectorized copy instead of an O(N)
        Python hashing loop."""
        from .fusedbatch import con_column_key
        self.absorb(sched)
        n = self.n
        for ci, con in enumerate(constraints):
            # node.ip constraints resolve to prefix-specific column
            # keys ("node.ip/<p>") whose per-node values _node_value
            # computes — the resident row maintenance below them is
            # key-agnostic (fill_constraints parity)
            col_key, expected = con_column_key(con)
            if col_key is None:
                # malformed node.ip: never matches, regardless of op
                con_op[ci] = 0
                con_exp[ci] = SENTINEL
                continue
            entry = self.con_cols.get(col_key)
            if entry is None:
                if len(self.con_cols) >= CON_CACHE_CAP:
                    del self.con_cols[next(iter(self.con_cols))]
                entry = _ConColumn(self.nb)
                self.con_cols[col_key] = entry
                for i, info in enumerate(self.infos):
                    self._recompute_con_row(col_key, i, info)
            if entry.none_count > 0:
                # unknown key on some node: node never matches,
                # regardless of op (fill_constraints parity)
                con_op[ci] = 0
                con_exp[ci] = SENTINEL
                continue
            con_hash[ci, :, :n] = entry.hash[:, :n]
            con_op[ci] = con.operator
            con_exp[ci] = split_hash(str_hash(expected))

    def flat_leaf(self, sched, descriptor: str
                  ) -> Tuple[np.ndarray, int]:
        """Streaming twin of ``fusedbatch.flat_leaf`` — leaf ids stay
        first-appearance ordered in ROW order (a tie-break the kernel
        reads), so value changes that would renumber rebuild the
        column."""
        self.absorb(sched)
        entry = self.leaf_cols.get(descriptor)
        if entry is None:
            from ..scheduler.nodeset import _pref_value
            if len(self.leaf_cols) >= LEAF_CACHE_CAP:
                self.leaf_cols.pop(next(iter(self.leaf_cols)))
            leaf = np.zeros(self.nb, np.int32)
            ids: Dict[str, int] = {}
            values: List[str] = []
            for i, info in enumerate(self.infos):
                v = _pref_value(info, descriptor) or ""
                values.append(v)
                leaf[i] = ids.setdefault(v, len(ids))
            entry = (leaf, ids, values)
            self.leaf_cols[descriptor] = entry
        leaf, ids, _values = entry
        return leaf, max(len(ids), 1)

    # --------------------------------------------------------- device tier

    def _device_upload(self, reason: str = "cold_build") -> None:
        """Fresh device placement of the five node-state columns (full
        rebuild, or a delta too wide for the scatter buckets).  Covers
        every row, so the host-only backlog is consumed by definition.
        On a mesh the wide-delta re-upload is STAGED PER SHARD:
        ``device_put`` with a node-axis NamedSharding ships each device
        its own nb/D slice directly."""
        if not self.device_enabled:
            return
        self._pending_dev_rows = {}
        mesh = self._mesh_for()
        try:
            with fusedbatch.x64():
                if mesh is not None:
                    from ..parallel.sharded import put_resident
                    self.dev = put_resident(
                        (self.valid, self.ready, self.cpu, self.mem,
                         self.total), mesh)
                    self._mesh_active = True
                else:
                    import jax.numpy as jnp
                    self.dev = tuple(jnp.asarray(a) for a in (
                        self.valid, self.ready, self.cpu, self.mem,
                        self.total))
                    self._mesh_active = False
        except Exception:
            log.exception("resident device upload failed; host tier only")
            self.device_enabled = False
            self.dev = None
            self._mesh_active = False
            _metrics.counter("swarm_streaming_device_disabled")
            return
        # host nbytes == device nbytes here (jnp.asarray copies the
        # host columns wholesale under the x64 guard)
        _devtel.note_h2d(reason, _devtel.tree_nbytes(
            (self.valid, self.ready, self.cpu, self.mem, self.total)))
        _devtel.set_watermark("device_resident",
                              _devtel.tree_nbytes(self.dev))
        self.stats["device_syncs"] += 1
        self._dev_version = self._tracker.version \
            if self._tracker is not None else -1

    def _device_sync(self, rows: List[int]) -> None:
        """Scatter dirty rows — plus any host-only backlog — into the
        resident device arrays via the donated update program; wide
        deltas re-upload wholesale.  On a mesh the dirty rows are
        bucketed by owning shard (row // local_n) and scattered by the
        per-shard donated program — each device updates only rows it
        owns, zero cross-device traffic."""
        if not self.device_enabled:
            return
        if self.dev is None:
            self._pending_dev_rows = {}
            self._device_upload()
            return
        mesh = self._mesh_for() if self._mesh_active else None
        if self._mesh_active and mesh is None:
            # the mesh became unusable under live device arrays (bucket
            # regrew to a non-dividing width): re-place
            self.dev = None
            self._device_upload()
            return
        if self._pending_dev_rows:
            backlog = self._pending_dev_rows
            self._pending_dev_rows = {}
            for i in rows:
                backlog[i] = None
            rows = list(backlog)
        if not rows:
            self._dev_version = self._tracker.version \
                if self._tracker is not None else -1
            return
        db = _d_bucket(len(rows))
        if db is None:
            self._device_upload(reason="wide_reupload")
            return
        from .planner import _jit_cache_size, _observe_compile
        import time as _time
        if mesh is not None:
            from ..parallel.sharded import NODE_AXIS
            nd = mesh.shape[NODE_AXIS]
            local_n = self.nb // nd
            # pad slot = local_n: out of bounds for the shard, drops
            idx = np.full((nd, db), local_n, np.int32)
            u_valid = np.zeros((nd, db), bool)
            u_ready = np.zeros((nd, db), bool)
            u_cpu = np.zeros((nd, db), np.int64)
            u_mem = np.zeros((nd, db), np.int64)
            u_total = np.zeros((nd, db), np.int32)
            fill = [0] * nd
            for i in rows:
                s, r = divmod(i, local_n)
                j = fill[s]
                fill[s] += 1
                idx[s, j] = r
                u_valid[s, j] = self.valid[i]
                u_ready[s, j] = self.ready[i]
                u_cpu[s, j] = self.cpu[i]
                u_mem[s, j] = self.mem[i]
                u_total[s, j] = self.total[i]
            bucket = f"stream_nb{self.nb}_d{db}_x{nd}"
            reason = "shard_scatter"
            probe = None   # resolved below (import-order safety)
        else:
            idx = np.full(db, self.nb, np.int32)   # pad = oob, drops
            idx[:len(rows)] = rows
            u_valid = np.zeros(db, bool)
            u_ready = np.zeros(db, bool)
            u_cpu = np.zeros(db, np.int64)
            u_mem = np.zeros(db, np.int64)
            u_total = np.zeros(db, np.int32)
            for j, i in enumerate(rows):
                u_valid[j] = self.valid[i]
                u_ready[j] = self.ready[i]
                u_cpu[j] = self.cpu[i]
                u_mem[j] = self.mem[i]
                u_total[j] = self.total[i]
            bucket = f"stream_nb{self.nb}_d{db}"
            reason = "dirty_scatter"
            probe = _scatter_rows_jit
        if probe is None:
            from ..parallel.sharded import scatter_rows_sharded
            probe = scatter_rows_sharded
        before = _jit_cache_size(probe)
        staged = _devtel.tree_nbytes(
            (idx, u_valid, u_ready, u_cpu, u_mem, u_total))
        _devtel.note_h2d(reason, staged)
        # what a non-streaming tick would have shipped instead: the
        # full five-column upload, minus what the scatter staged
        full = _devtel.tree_nbytes(
            (self.valid, self.ready, self.cpu, self.mem, self.total))
        avoided = max(0, full - staged)
        _devtel.note_bytes_avoided(avoided)
        self.stats["bytes_avoided"] += avoided
        # the resident buffers are DONATED to the scatter program: the
        # old array objects are dead after this call, and the donation
        # balance catches anyone who kept a reference and reads them
        old_ids = [id(a) for a in self.dev]
        _devtel.note_donated(old_ids)
        t0 = _time.perf_counter()
        try:
            with warnings.catch_warnings():
                # CPU backends may decline donation with a warning; the
                # program is correct either way (donation is the TPU win)
                warnings.filterwarnings("ignore", message=".*onat.*")
                with fusedbatch.x64():
                    if mesh is not None:
                        from ..parallel.sharded import (
                            put_scatter_updates, scatter_rows_sharded)
                        bufs = put_scatter_updates(
                            (idx, u_valid, u_ready, u_cpu, u_mem,
                             u_total), mesh)
                        self.dev = scatter_rows_sharded(
                            *self.dev, *bufs, mesh=mesh)
                        self.stats["shard_syncs"] += 1
                    else:
                        self.dev = _scatter_rows_jit(
                            *self.dev, idx, u_valid, u_ready, u_cpu,
                            u_mem, u_total)
        except Exception:
            log.exception("resident device scatter failed; re-uploading")
            _devtel.note_retired(old_ids)   # buffers gone either way
            self.dev = None
            self._device_upload()
            return
        dt = _time.perf_counter() - t0
        _devtel.note_retired(old_ids)
        comp = _observe_compile(probe, bucket, before, dt)
        _devtel.note_kernel(bucket, "scatter", dispatch_s=dt,
                            compile_s=comp, node_rows=len(rows))
        _devtel.set_watermark("device_resident",
                              _devtel.tree_nbytes(self.dev))
        self.stats["device_syncs"] += 1
        self._dev_version = self._tracker.version \
            if self._tracker is not None else -1

    def device_carry(self):
        """The resident device columns (valid, ready, cpu, mem, total)
        — only when they provably mirror the host columns (no marks
        since the last sync); None otherwise.  Consumers treat them as
        immutable snapshots (jax arrays are)."""
        if self.dev is None or self._tracker is None:
            return None
        if self._tracker.version != self._dev_version \
                or self._tracker.pending:
            return None
        # donation-balance runtime check: a consumer is about to read
        # these arrays — if any was donated to a scatter and never
        # rebound, that read would be use-after-donation
        _devtel.check_live([id(a) for a in self.dev])
        return self.dev

    # --------------------------------------------------------------- bench

    def snapshot(self) -> Dict[str, object]:
        """Artifact-shaped stats: the ``streaming_*`` fields bench and
        bench_compare gate on."""
        return {
            "enabled": True,
            "dirty_frac": round(self.stats["dirty_frac"], 4),
            "resyncs": self.stats["resyncs"],
            "fallbacks": self.stats["fallbacks"],
            "incremental_ticks": self.stats["incremental"],
            "full_ticks": self.stats["full"],
            "rows": self.stats["rows"],
            "device_syncs": self.stats["device_syncs"],
            "bytes_avoided": self.stats["bytes_avoided"],
            "shard_syncs": self.stats["shard_syncs"],
            "mesh_devices": self._mesh_devices(),
        }

    def _mesh_devices(self) -> int:
        if not self._mesh_active or self.mesh is None:
            return 0
        from ..parallel.sharded import NODE_AXIS
        return int(self.mesh.shape[NODE_AXIS])
