from .autoscaler import Supervisor as AutoscaleSupervisor
from .enforcers import ConstraintEnforcer, VolumeEnforcer
from .global_ import Orchestrator as GlobalOrchestrator
from .jobs import Orchestrator as JobsOrchestrator
from .replicated import Orchestrator as ReplicatedOrchestrator
from .restart import Supervisor as RestartSupervisor
from .taskreaper import TaskReaper
from .update import Supervisor as UpdateSupervisor

__all__ = [
    "AutoscaleSupervisor", "ConstraintEnforcer", "GlobalOrchestrator",
    "JobsOrchestrator", "ReplicatedOrchestrator", "RestartSupervisor",
    "TaskReaper", "UpdateSupervisor", "VolumeEnforcer",
]
