from .global_ import Orchestrator as GlobalOrchestrator
from .replicated import Orchestrator as ReplicatedOrchestrator
from .restart import Supervisor as RestartSupervisor
from .taskreaper import TaskReaper
from .update import Supervisor as UpdateSupervisor

__all__ = [
    "GlobalOrchestrator", "ReplicatedOrchestrator", "RestartSupervisor",
    "TaskReaper", "UpdateSupervisor",
]
