"""Horizontal autoscaler: the load-reactive control loop (ISSUE 12).

One ``AutoscaleSupervisor`` per manager scales every replicated service
carrying an ``AutoscaleConfig`` (models/specs.py) from observed load —
per-service utilization through the **sampler seam**, or the
pending->assigned p99 from the obs lifecycle timers.  The loop is the
established threadless-drivable FSM shape (orchestrator/update.py,
restart.py): production wraps one thread (``start_worker=True``); the
deterministic simulator constructs ``start_worker=False`` and pumps
``drive()`` from the leader's control step under virtual time.

Stability machinery, in decision order per service:

* **flap breaker** — a policy that reversed direction
  ``flap_reversals`` times inside the flap window freezes itself for a
  window (no writes) and raises the ``autoscale_flapping`` health warn;
  chaos-induced metric noise can never oscillate replicas.
* **hysteresis** — a deadband of ±``hysteresis`` around the target;
  utilization inside it produces no decision at all.
* **rate limit** — at most one step per ``stabilization_window``, per
  service.
* **bounds** — the step is clamped into [min, max] replicas
  (``_enforce_bounds`` is the checker-sensitivity seam: with it off,
  the sim's ``autoscale-within-bounds-and-rate`` invariant must fire).

Every decision writes the service spec through ``store.update`` — the
proposal is pinned to the leadership epoch read at commit start, so a
deposed leader's scale writes are fenced — and the SAME transaction
stamps ``Service.autoscale_status`` (objects.py): the successor's
supervisor resumes the window/direction/freeze state from the
replicated row, which is what lets an in-flight scale-up survive
failover without violating the rate invariant.

All deadlines read ``models.types.now()``.  Gauges:
``swarm_autoscale_replicas{service=}``,
``swarm_autoscale_flapping{service=}``,
``swarm_autoscale_out_of_bounds{service=}``; decisions count on
``swarm_autoscale_decisions{direction=,service=}``.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Callable, Dict, Optional

from ..models.objects import AutoscaleStatus, Service
from ..models.specs import ServiceMode
from ..models.types import now
from ..state.store import MemoryStore, WriteTx
from ..utils.metrics import registry as _metrics

log = logging.getLogger("autoscaler")

#: flap window = this many stabilization windows: reversals older than
#: it age out; a freeze lasts one flap window
FLAP_WINDOW_FACTOR = 4.0


def registry_sampler(registry=None) -> Callable[[str], Optional[dict]]:
    """Production sampler: per-service load from the
    ``swarm_service_load{service=}`` gauge (exported by whatever
    measures demand — an ingress proxy, a queue depth exporter) and the
    pending->assigned p99 from the obs lifecycle timers — the SERVICE'S
    OWN ``swarm_task_lifecycle_service{service=}`` timer when it has
    samples (a quiet service must not scale on a noisy neighbor's
    latency), the cluster-wide edge timer as the fallback for services
    past the bounded per-service cardinality cap.  The sim replaces
    this wholesale with a deterministic scenario-driven sampler — that
    indirection is the whole point of the seam."""
    from ..obs.lifecycle import service_edge_timer_name
    reg = registry if registry is not None else _metrics

    def sample(service_id: str) -> Optional[dict]:
        out = {}
        load = reg.get_gauge(
            f'swarm_service_load{{service="{service_id}"}}')
        if load is not None:
            out["load"] = load
        t = reg.get_timer(service_edge_timer_name(service_id))
        if t is None or not t.count:
            t = reg.get_timer(
                'swarm_task_lifecycle{from="pending",to="assigned"}')
        if t is not None and t.count:
            out["p99"] = t.quantiles()[0.99]
        return out or None

    return sample


class Supervisor:
    """One decision pass per ``drive()`` over every autoscaled service."""

    #: checker-sensitivity seam (tests/test_autoscale.py): False skips
    #: BOTH the [min, max] clamp and the stabilization-window rate
    #: limit — the sim's ``autoscale-within-bounds-and-rate`` invariant
    #: must then catch the runaway policy.
    _enforce_bounds = True
    #: checker-sensitivity seam: False ignores scale-down decisions —
    #: load removal then never converges replicas back to min and the
    #: ``autoscale-converges`` expectation must fire.
    _scale_down_enabled = True

    def __init__(self, store: MemoryStore,
                 sampler: Optional[Callable[[str], Optional[dict]]] = None,
                 start_worker: bool = True, interval: float = 2.0):
        self.store = store
        self.sampler = sampler if sampler is not None \
            else registry_sampler()
        self.interval = interval
        self.threadless = not start_worker
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"decisions": 0, "frozen_skips": 0,
                      "rate_limited": 0}

    # --------------------------------------------------------------- running

    def start(self) -> None:
        """Production mode: one daemon thread, drive every interval."""
        if self.threadless or (self._thread is not None
                               and self._thread.is_alive()):
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.drive()
                except Exception:
                    log.exception("autoscale pass failed")

        self._thread = threading.Thread(target=loop, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Teardown without store writes (deposed-leader discipline)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ---------------------------------------------------------------- drive

    def drive(self) -> None:
        """One synchronous decision pass.  Threadless mode re-raises
        store failures (leadership loss) to the caller — the sim's
        control step handles the deposal, exactly like the update and
        restart supervisors."""
        services = self.store.view(lambda tx: tx.find(Service))
        for svc in sorted(services, key=lambda s: s.id):
            cfg = svc.spec.autoscale
            if cfg is None or svc.spec.mode != ServiceMode.REPLICATED \
                    or svc.spec.replicated is None:
                continue
            try:
                self._drive_service(svc, cfg)
            except Exception:
                if self.threadless:
                    raise
                log.exception("autoscale decision for %s failed", svc.id)

    def _drive_service(self, svc: Service, cfg) -> None:
        sid = svc.id
        cur = svc.spec.replicated.replicas
        ts = now()
        st = svc.autoscale_status or AutoscaleStatus()
        _metrics.gauge(f'swarm_autoscale_replicas{{service="{sid}"}}',
                       float(cur))
        oob = not (cfg.min_replicas <= cur <= cfg.max_replicas)
        _metrics.gauge(
            f'swarm_autoscale_out_of_bounds{{service="{sid}"}}',
            1.0 if oob else 0.0)

        window = max(cfg.stabilization_window, 0.0)
        flap_window = window * FLAP_WINDOW_FACTOR
        frozen = ts < st.frozen_until
        _metrics.gauge(f'swarm_autoscale_flapping{{service="{sid}"}}',
                       1.0 if frozen else 0.0)
        if frozen:
            # flap breaker engaged: policy writes suspended for a flap
            # window (the health plane warns meanwhile)
            self.stats["frozen_skips"] += 1
            return

        want, direction = self._desired(sid, cfg, cur)
        if direction == 0:
            return
        if direction < 0 and not self._scale_down_enabled:
            return   # sensitivity seam: converge enforcement off
        if self._enforce_bounds:
            want = max(cfg.min_replicas,
                       min(cfg.max_replicas, want))
            if want == cur:
                return
            # rate limit: one step per stabilization window, judged
            # against the REPLICATED stamp so it holds across failover
            if st.last_decision_at and ts - st.last_decision_at < window:
                self.stats["rate_limited"] += 1
                return
        elif want == cur:
            return

        # flap detection BEFORE the write: a direction reversal joins
        # the window; too many reversals freeze the policy instead of
        # committing yet another oscillation
        reversals = [r for r in st.reversal_stamps
                     if flap_window <= 0 or ts - r < flap_window]
        if st.last_direction and direction != st.last_direction:
            reversals.append(ts)
            if cfg.flap_reversals > 0 \
                    and len(reversals) >= cfg.flap_reversals:
                self._freeze(sid, st, reversals, ts,
                             flap_window if flap_window > 0
                             else window)
                return

        self._commit(svc, cfg, want, direction, reversals, ts)

    # ---------------------------------------------------------------- policy

    def _desired(self, sid: str, cfg, cur: int):
        """(want, direction) from the sampled signal; direction 0 =
        inside the hysteresis deadband or no sample."""
        sample = self.sampler(sid)
        if not sample:
            return cur, 0
        signal = target = None
        if cfg.target_utilization > 0 and sample.get("load") is not None:
            signal = sample["load"] / max(cur, 1)
            target = cfg.target_utilization
        elif cfg.target_p99 > 0 and sample.get("p99") is not None:
            signal = sample["p99"]
            target = cfg.target_p99
        if signal is None:
            return cur, 0
        if signal > target * (1.0 + cfg.hysteresis):
            if cfg.target_utilization > 0:
                # jump toward the load-proportional size, bounded by the
                # step: big bursts converge in few windows, small ones
                # take one step
                ideal = math.ceil(sample["load"] / target)
                want = min(cur + max(cfg.scale_up_step, 1),
                           max(ideal, cur + 1))
            else:
                want = cur + max(cfg.scale_up_step, 1)
            return want, 1
        if signal < target * (1.0 - cfg.hysteresis):
            return cur - max(cfg.scale_down_step, 1), -1
        return cur, 0

    # ---------------------------------------------------------------- writes

    def _freeze(self, sid: str, st: AutoscaleStatus, reversals,
                ts: float, hold: float) -> None:
        """Engage the flap breaker: one status-only write (no replica
        change) so the freeze itself rides the replicated row and
        survives failover."""
        until = ts + max(hold, 1.0)

        def cb(tx: WriteTx) -> None:
            cur = tx.get(Service, sid)
            if cur is None or cur.spec.autoscale is None:
                return
            cur = cur.copy()
            status = cur.autoscale_status or AutoscaleStatus()
            status = status.copy()
            status.reversal_stamps = list(reversals)
            status.frozen_until = until
            cur.autoscale_status = status
            tx.update(cur)

        self._update(cb, "freeze flapping policy")
        _metrics.counter(f'swarm_autoscale_flaps{{service="{sid}"}}')
        _metrics.gauge(f'swarm_autoscale_flapping{{service="{sid}"}}',
                       1.0)
        log.warning("autoscale policy for %s frozen until %.1f "
                    "(%d direction reversals)", sid, until,
                    len(reversals))

    def _commit(self, svc: Service, cfg, want: int, direction: int,
                reversals, ts: float) -> None:
        sid = svc.id
        state: Dict[str, bool] = {}

        def cb(tx: WriteTx) -> None:
            cur = tx.get(Service, sid)
            if cur is None or cur.spec.autoscale is None \
                    or cur.spec.replicated is None:
                return
            if cur.spec.replicated.replicas != \
                    svc.spec.replicated.replicas:
                return   # a concurrent writer moved it; re-decide later
            cur = cur.copy()
            cur.spec.replicated.replicas = want
            status = (cur.autoscale_status or AutoscaleStatus()).copy()
            status.last_decision_at = ts
            status.last_direction = direction
            status.reversal_stamps = list(reversals)
            cur.autoscale_status = status
            tx.update(cur)
            state["written"] = True

        self._update(cb, "scale service")
        if not state.get("written"):
            return
        self.stats["decisions"] += 1
        label = "up" if direction > 0 else "down"
        _metrics.counter(
            f'swarm_autoscale_decisions{{direction="{label}",'
            f'service="{sid}"}}')
        _metrics.gauge(f'swarm_autoscale_replicas{{service="{sid}"}}',
                       float(want))
        log.info("autoscaled %s: %d -> %d (%s)", sid,
                 svc.spec.replicated.replicas, want, label)

    def _update(self, cb, what: str) -> None:
        try:
            self.store.update(cb)
        except Exception:
            if self.threadless:
                raise   # sim: leadership loss must reach the control step
            log.exception("failed to %s", what)
