"""Shared orchestration helpers: slots, task factory, dirtiness checks.

Reference: manager/orchestrator/{task.go,slot.go,service.go}.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..models.objects import Cluster, Node, Service, Task
from ..models.specs import ServiceMode
from ..models.types import (
    Endpoint, NodeAvailability, NodeState, RestartCondition, TaskState,
    TaskStatus, UpdateConfig, UpdateFailureAction, now,
)
from ..scheduler import constraint as constraint_mod
# the single priority accessor — defined next to the selection logic
# that consumes it, re-exported here for orchestrator-side callers
from ..scheduler.preempt import task_priority  # noqa: F401
from ..state.store import Batch, MemoryStore, ReadTx
from ..utils import new_id

# compile-time defaults (reference: api/defaults/service.go)
DEFAULT_RESTART_DELAY = 5.0
DEFAULT_UPDATE_CONFIG = UpdateConfig(
    parallelism=1, failure_action=UpdateFailureAction.PAUSE, monitor=30.0)
DEFAULT_ROLLBACK_CONFIG = UpdateConfig(
    parallelism=1, failure_action=UpdateFailureAction.PAUSE, monitor=30.0)

# Slot: the running tasks occupying one slot; usually a single task, but
# rolling updates can briefly hold two (reference: slot.go:11).
Slot = List[Task]


@dataclass(frozen=True)
class SlotTuple:
    """(service, slot) for replicated; (service, node) for global."""

    service_id: str
    slot: int = 0
    node_id: str = ""


def slot_tuple(t: Task) -> SlotTuple:
    if t.slot:
        return SlotTuple(service_id=t.service_id, slot=t.slot)
    return SlotTuple(service_id=t.service_id, node_id=t.node_id)


def is_replicated_service(service: Optional[Service]) -> bool:
    return service is not None and service.spec.mode == ServiceMode.REPLICATED


def is_global_service(service: Optional[Service]) -> bool:
    return service is not None and service.spec.mode == ServiceMode.GLOBAL


def is_replicated_job(service: Optional[Service]) -> bool:
    return service is not None and \
        service.spec.mode == ServiceMode.REPLICATED_JOB


def is_global_job(service: Optional[Service]) -> bool:
    return service is not None and service.spec.mode == ServiceMode.GLOBAL_JOB


def invalid_node(n: Optional[Node]) -> bool:
    """Node is nil, down, or drained (reference: service.go InvalidNode)."""
    return (n is None
            or n.status.state == NodeState.DOWN
            or n.spec.availability == NodeAvailability.DRAIN)


def effective_task_spec(service: Service):
    """The task spec a task of this service actually carries: the
    service-level priority class propagates into the spec at creation
    when the task spec has none (the scheduler only reads
    ``task.spec.priority``).  ``is_task_dirty`` compares against this
    same spec so the propagation never reads as spec drift."""
    spec = service.spec.task
    svc_prio = getattr(service.spec, "priority", 0)
    if svc_prio and not getattr(spec, "priority", 0):
        spec = spec.copy()
        spec.priority = svc_prio
    return spec


def new_task(cluster: Optional[Cluster], service: Service, slot: int,
             node_id: str = "") -> Task:
    """Task factory (reference: task.go:16 NewTask)."""
    log_driver = service.spec.task.log_driver
    if log_driver is None and cluster is not None:
        log_driver = cluster.spec.task_defaults.log_driver

    task = Task(
        id=new_id(),
        service_annotations=service.spec.annotations,
        spec=effective_task_spec(service),
        spec_version=service.spec_version.copy()
        if service.spec_version else None,
        service_id=service.id,
        slot=slot,
        status=TaskStatus(state=TaskState.NEW, timestamp=now(),
                          message="created"),
        endpoint=Endpoint(spec=service.spec.endpoint.copy())
        if service.spec.endpoint else None,
        desired_state=TaskState.RUNNING,
        log_driver=log_driver,
    )
    if node_id:
        task.node_id = node_id
    return task


def restart_condition(task: Task) -> RestartCondition:
    if task.spec.restart is not None:
        return task.spec.restart.condition
    return RestartCondition.ANY


def task_timestamp(t: Task) -> float:
    return t.status.applied_at or t.status.timestamp


def _node_matches(service: Service, n: Optional[Node]) -> bool:
    if n is None:
        return False
    try:
        constraints = constraint_mod.parse(
            service.spec.task.placement.constraints)
    except constraint_mod.InvalidConstraint:
        constraints = []
    return constraint_mod.node_matches(constraints, n)


def is_task_dirty(service: Service, t: Task, n: Optional[Node]) -> bool:
    """Does the task need replacing to match the service spec?
    (reference: task.go:75 IsTaskDirty)"""
    if (t.spec_version is not None and service.spec_version is not None
            and t.spec_version.index == service.spec_version.index):
        return False

    # compare against the spec tasks are actually minted with — a
    # service-level priority propagated at creation is not drift
    service_spec = effective_task_spec(service)

    # Not dirty if only placement constraints changed and the assigned node
    # still satisfies them.
    if _placement_constraints_only_changed(service_spec, t) \
            and _node_matches(service, n):
        return False

    spec_equal = service_spec == t.spec or \
        dataclasses.asdict(service_spec) == dataclasses.asdict(t.spec)
    endpoint_dirty = False
    if t.endpoint is not None:
        svc_ep = service.spec.endpoint
        task_ep_spec = t.endpoint.spec
        if svc_ep is None:
            endpoint_dirty = bool(task_ep_spec.ports)
        else:
            endpoint_dirty = dataclasses.asdict(svc_ep) != \
                dataclasses.asdict(task_ep_spec)
    return (not spec_equal) or endpoint_dirty


def _placement_constraints_only_changed(service_spec, t: Task) -> bool:
    if dataclasses.asdict(service_spec.placement) == \
            dataclasses.asdict(t.spec.placement):
        return False
    a = dataclasses.asdict(service_spec)
    b = dataclasses.asdict(t.spec)
    a["placement"] = b["placement"]
    return a == b


def set_service_tasks_remove(store: MemoryStore, service: Service) -> None:
    """Mark all of a deleted service's tasks desired-REMOVE so agents shut
    them down and the reaper deletes them (reference: service.go
    SetServiceTasksRemove)."""
    from ..state.store import ByService

    tasks = store.view(lambda tx: tx.find(Task, ByService(service.id)))

    def cb(batch: Batch) -> None:
        for t in tasks:
            if t.desired_state == TaskState.REMOVE:
                continue

            def one(tx, t=t):
                cur = tx.get(Task, t.id)
                if cur is None:
                    return
                cur = cur.copy()
                cur.desired_state = TaskState.REMOVE
                tx.update(cur)
            batch.update(one)

    store.batch(cb)


def update_config_for(service: Service, rollback: bool) -> UpdateConfig:
    if rollback:
        return service.spec.rollback or DEFAULT_ROLLBACK_CONFIG
    return service.spec.update or DEFAULT_UPDATE_CONFIG
