"""Constraint and volume enforcers.

Reference: manager/orchestrator/constraintenforcer/constraint_enforcer.go
and manager/orchestrator/volumeenforcer/volume_enforcer.go.

The constraint enforcer shuts down running tasks whose node no longer
satisfies their placement constraints or resource reservations after a node
update (labels removed, resources shrunk).  The volume enforcer removes
tasks using volumes whose availability was set to DRAIN.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ..models.objects import Node, Service, Task, Volume
from ..models.types import NodeAvailability, TaskState, VolumeAvailability
from ..scheduler import constraint as constraint_mod
from ..state.events import Event
from ..state.store import Batch, ByNode, MemoryStore
from ..state.watch import Closed

log = logging.getLogger("enforcer")


class _EnforcerLoop:
    name = "enforcer"

    def __init__(self, store: MemoryStore):
        self.store = store
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name=self.name,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)

    def run(self) -> None:
        try:
            _, sub = self.store.view_and_watch(
                self._init, predicate=self._pred, accepts_blocks=True)
            try:
                self._initial_pass()
                while not self._stop.is_set():
                    try:
                        event = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(event, Event):
                        self._handle(event)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _init(self, tx) -> None:
        pass

    def _initial_pass(self) -> None:
        pass

    def _pred(self, ev) -> bool:
        return isinstance(ev, Event)

    def _handle(self, ev: Event) -> None:
        raise NotImplementedError


class ConstraintEnforcer(_EnforcerLoop):
    """reference: constraint_enforcer.go:33."""

    name = "constraint-enforcer"

    def _init(self, tx) -> None:
        self._initial_nodes = tx.find(Node)

    def _initial_pass(self) -> None:
        # check all nodes once at startup (reference: Run's initial scan)
        for node in self._initial_nodes:
            self.reject_noncompliant_tasks(node)

    def _pred(self, ev) -> bool:
        return (isinstance(ev, Event) and isinstance(ev.obj, Node)
                and ev.action == "update")

    def _handle(self, ev: Event) -> None:
        self.reject_noncompliant_tasks(ev.obj)

    def reject_noncompliant_tasks(self, node: Node) -> None:
        # drain is the orchestrators' job; pause means hands off
        if node.spec.availability != NodeAvailability.ACTIVE:
            return

        def read(tx):
            tasks = tx.find(Task, ByNode(node.id))
            services = {t.service_id: tx.get(Service, t.service_id)
                        for t in tasks if t.service_id}
            return tasks, services

        tasks, services = self.store.view(read)

        available_cpu = available_mem = 0
        if node.description and node.description.resources:
            available_cpu = node.description.resources.nano_cpus
            available_mem = node.description.resources.memory_bytes

        remove: List[Task] = []
        for t in tasks:
            if t.desired_state < TaskState.ASSIGNED or \
                    t.desired_state > TaskState.COMPLETE:
                continue
            # use the service's CURRENT constraints: the task's copy can be
            # outdated after constraint-only service updates
            # (reference: constraint_enforcer.go:121 comment)
            service = services.get(t.service_id)
            placement = (service.spec.task.placement if service is not None
                         else t.spec.placement)
            if placement is not None and placement.constraints:
                try:
                    constraints = constraint_mod.parse(placement.constraints)
                except constraint_mod.InvalidConstraint:
                    constraints = []
                if not constraint_mod.node_matches(constraints, node):
                    remove.append(t)
                    continue
            res = t.spec.resources.reservations if t.spec.resources else None
            if res is not None:
                if res.memory_bytes > available_mem or \
                        res.nano_cpus > available_cpu:
                    remove.append(t)
                    continue
                available_mem -= res.memory_bytes
                available_cpu -= res.nano_cpus

        if not remove:
            return

        def cb(batch: Batch) -> None:
            for t in remove:
                def one(tx, t=t):
                    cur = tx.get(Task, t.id)
                    if cur is None or \
                            cur.desired_state > TaskState.RUNNING:
                        return
                    cur = cur.copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    if cur.status.state < TaskState.ASSIGNED:
                        cur.status.state = TaskState.SHUTDOWN
                        cur.status.err = \
                            "assigned node no longer meets constraints"
                    tx.update(cur)
                batch.update(one)

        try:
            self.store.batch(cb)
            log.info("shut down %d noncompliant tasks on node %s",
                     len(remove), node.id)
        except Exception:
            log.exception("constraint enforcement batch failed")


class VolumeEnforcer(_EnforcerLoop):
    """reference: volume_enforcer.go."""

    name = "volume-enforcer"

    def _pred(self, ev) -> bool:
        return (isinstance(ev, Event) and isinstance(ev.obj, Volume)
                and ev.action == "update")

    def _handle(self, ev: Event) -> None:
        volume = ev.obj
        if volume.spec.availability != VolumeAvailability.DRAIN:
            return
        tasks = self.store.view(lambda tx: tx.find(Task))
        using = [t for t in tasks
                 if any(va.id == volume.id for va in t.volumes)
                 and t.desired_state <= TaskState.RUNNING]
        if not using:
            return

        def cb(batch: Batch) -> None:
            for t in using:
                def one(tx, t=t):
                    cur = tx.get(Task, t.id)
                    if cur is None or \
                            cur.desired_state > TaskState.RUNNING:
                        return
                    cur = cur.copy()
                    cur.desired_state = TaskState.REMOVE
                    tx.update(cur)
                batch.update(one)

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("volume enforcement batch failed")
