"""Global-service orchestrator: one task per constraint-matching node.

Reference: manager/orchestrator/global/global.go.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ..models.objects import Cluster, Node, Service, Task
from ..models.types import NodeAvailability, NodeState, TaskState
from ..obs.trace import tracer
from ..scheduler import constraint as constraint_mod
from ..state.events import Event, EventCommit, EventSnapshotRestore
from ..state.store import Batch, ByName, ByNode, ByService, MemoryStore
from ..state.watch import Closed
from ..utils.metrics import registry as _metrics
from . import common
from .replicated import DEFAULT_CLUSTER_NAME
from .restart import Supervisor as RestartSupervisor
from .update import Supervisor as UpdateSupervisor
from . import taskinit

log = logging.getLogger("global")

# cached Timer reference (Registry.reset() resets in place)
_RECONCILE_TIMER = _metrics.timer(
    'swarm_orchestrator_reconcile{kind="global"}')


class _GlobalService:
    __slots__ = ("service", "constraints")

    def __init__(self, service: Service):
        self.service = service
        self.constraints = []
        placement = service.spec.task.placement
        if placement and placement.constraints:
            try:
                self.constraints = constraint_mod.parse(placement.constraints)
            except constraint_mod.InvalidConstraint:
                self.constraints = []


class Orchestrator:
    def __init__(self, store: MemoryStore,
                 restarts: Optional[RestartSupervisor] = None,
                 updater: Optional[UpdateSupervisor] = None):
        self.store = store
        self.restarts = restarts or RestartSupervisor(store)
        self.updater = updater or UpdateSupervisor(store, self.restarts)
        self.cluster: Optional[Cluster] = None
        self.nodes: Dict[str, Node] = {}      # non-drained, non-down nodes
        self.global_services: Dict[str, _GlobalService] = {}
        self.restart_tasks: Dict[str, None] = {}   # insertion-ordered set
        # victims whose preemption marker already triggered a reconcile
        # (pruned on task delete; see _handle_task_change)
        self._preempt_seen: set = set()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="global",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)
        self.updater.cancel_all()
        self.restarts.cancel_all()

    def run(self) -> None:
        try:
            reconcile_ids: List[str] = []

            def init(tx):
                for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                    self.cluster = c
                for n in tx.find(Node):
                    self._update_node(n)
                for s in tx.find(Service):
                    if common.is_global_service(s):
                        self._update_service(s)
                        reconcile_ids.append(s.id)

            # accepts_blocks: assignment blocks (state<=RUNNING) are not
            # failures; _handle_task_change only reacts to state>RUNNING
            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            try:
                # outside view_and_watch: check_tasks writes through
                # store.batch, which needs the update lock view_and_watch
                # holds; the events it causes replay through sub (idempotent)
                taskinit.check_tasks(self.store, self.store.view(), self,
                                     self.restarts)
                self._tick_tasks()
                self._reconcile_services(reconcile_ids)

                while not self._stop.is_set():
                    try:
                        event = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(event, EventSnapshotRestore):
                        self._resync()
                    elif isinstance(event, Event):
                        self._handle_event(event)
                    self._tick_tasks()
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _resync(self) -> None:
        self.nodes.clear()
        self.global_services.clear()
        self.restart_tasks.clear()
        ids: List[str] = []

        def init(tx):
            for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                self.cluster = c
            for n in tx.find(Node):
                self._update_node(n)
            for s in tx.find(Service):
                if common.is_global_service(s):
                    self._update_service(s)
                    ids.append(s.id)

        self.store.view(init)
        self._reconcile_services(ids)

    # ----------------------------------------------------------- event intake

    def _handle_event(self, ev: Event) -> None:
        obj = ev.obj
        if isinstance(obj, Cluster):
            if ev.action != "delete":
                self.cluster = obj
        elif isinstance(obj, Service):
            if not common.is_global_service(obj):
                return
            if ev.action == "delete":
                common.set_service_tasks_remove(self.store, obj)
                self.global_services.pop(obj.id, None)
                self.restarts.clear_service_history(obj.id)
            else:
                self._update_service(obj)
                self._reconcile_services([obj.id])
        elif isinstance(obj, Node):
            if ev.action == "delete":
                self._foreach_task_from_node(obj, self._delete_task)
                self.nodes.pop(obj.id, None)
            else:
                self._update_node(obj)
                self._reconcile_one_node(obj)
        elif isinstance(obj, Task) and ev.action == "update":
            self._handle_task_change(obj)
        elif isinstance(obj, Task) and ev.action == "delete":
            self._preempt_seen.discard(obj.id)
            # beyond the reference (global.go:164 only watches updates):
            # an out-of-band deletion (operator `task rm`) of a live
            # global task would otherwise leave its node without a
            # replica until an unrelated event arrives
            if (obj.service_id in self.global_services
                    and obj.desired_state <= TaskState.RUNNING):
                self._reconcile_services([obj.service_id])

    def _handle_task_change(self, t: Task) -> None:
        if t.service_id not in self.global_services:
            return
        if t.desired_state > TaskState.RUNNING:
            # preempted by the scheduler: the node lost its replica with
            # no node/service event to notice — reconcile to re-cover
            # it, ONCE per victim (the marker persists through the
            # victim's remaining lifecycle writes)
            if "swarm.preempted.at" in t.annotations.labels \
                    and t.id not in self._preempt_seen:
                self._preempt_seen.add(t.id)
                self._reconcile_services([t.service_id])
            return
        if t.status.state > TaskState.RUNNING:
            self.restart_tasks[t.id] = None

    # --------------------------------------------------------------- mirrors

    def _update_node(self, node: Node) -> None:
        if node.spec.availability == NodeAvailability.DRAIN or \
                node.status.state == NodeState.DOWN:
            self.nodes.pop(node.id, None)
        else:
            self.nodes[node.id] = node

    def _update_service(self, service: Service) -> None:
        self.global_services[service.id] = _GlobalService(service)

    # ------------------------------------------------------------- reconcile

    def _reconcile_services(self, service_ids: List[str]) -> None:
        """reference: global.go:254 reconcileServices."""
        with tracer.span("orchestrator.reconcile", "orchestrator",
                         kind="global", services=len(service_ids)), \
                _RECONCILE_TIMER.time():
            self._reconcile_services_inner(service_ids)

    def _reconcile_services_inner(self, service_ids: List[str]) -> None:
        node_tasks: Dict[str, Dict[str, List[Task]]] = {}

        def read(tx):
            for service_id in service_ids:
                entry = self.global_services.get(service_id)
                if entry is None:
                    continue
                by_node: Dict[str, List[Task]] = {}
                for t in tx.find(Task, ByService(service_id)):
                    by_node.setdefault(t.node_id, []).append(t)
                for node_id in list(by_node):
                    updatable = self.restarts.updatable_tasks_in_slot(
                        by_node[node_id], entry.service)
                    if updatable:
                        by_node[node_id] = updatable
                    else:
                        del by_node[node_id]
                node_tasks[service_id] = by_node

        self.store.view(read)

        updates: List[tuple] = []

        def cb(batch: Batch) -> None:
            for service_id in service_ids:
                if service_id not in node_tasks:
                    continue
                entry = self.global_services[service_id]
                update_slots: List[List[Task]] = []
                by_node = node_tasks[service_id]
                for node_id, node in self.nodes.items():
                    meets = constraint_mod.node_matches(
                        entry.constraints, node)
                    ntasks = by_node.pop(node_id, [])
                    if not meets:
                        self._shutdown_tasks(batch, ntasks)
                        continue
                    if node.spec.availability == NodeAvailability.PAUSE:
                        continue
                    if not ntasks:
                        self._add_task(batch, entry.service, node_id)
                    else:
                        update_slots.append(ntasks)
                if update_slots:
                    updates.append((entry.service, update_slots))
                # tasks on nodes that are drained or no longer exist
                for ntasks in by_node.values():
                    self._shutdown_tasks(batch, ntasks)

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("global reconcile batch failed")

        for service, update_slots in updates:
            self.updater.update(self.cluster, service, update_slots)

    def _reconcile_one_node(self, node: Node) -> None:
        """reference: global.go:374 reconcileOneNode."""
        if node.spec.availability == NodeAvailability.DRAIN or \
                node.status.state == NodeState.DOWN:
            self._foreach_task_from_node(node, self._shutdown_task)
            return
        if node.spec.availability == NodeAvailability.PAUSE:
            return
        node = self.nodes.get(node.id)
        if node is None:
            return

        tasks_on_node = self.store.view(
            lambda tx: tx.find(Task, ByNode(node.id)))
        by_service: Dict[str, List[Task]] = {}
        for t in tasks_on_node:
            if t.service_id in self.global_services:
                by_service.setdefault(t.service_id, []).append(t)
        for service_id in list(by_service):
            entry = self.global_services[service_id]
            updatable = self.restarts.updatable_tasks_in_slot(
                by_service[service_id], entry.service)
            if updatable:
                by_service[service_id] = updatable
            else:
                del by_service[service_id]

        def cb(batch: Batch) -> None:
            for service_id, entry in self.global_services.items():
                if not constraint_mod.node_matches(entry.constraints, node):
                    continue
                tasks = by_service.get(service_id, [])
                if not tasks:
                    self._add_task(batch, entry.service, node.id)
                else:
                    # not a rolling update: this is node reconciliation
                    # (reference: global.go:440 comment)
                    dirty = []
                    clean = []
                    for t in tasks:
                        if common.is_task_dirty(entry.service, t, node):
                            dirty.append(t)
                        else:
                            clean.append(t)
                    if not clean:
                        self._add_task(batch, entry.service, node.id)
                    else:
                        dirty.extend(clean[1:])
                    self._shutdown_tasks(batch, dirty)

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("global reconcileOneNode batch failed")

    # ----------------------------------------------------------------- ticks

    def _tick_tasks(self) -> None:
        if not self.restart_tasks:
            return
        restart_tasks, self.restart_tasks = self.restart_tasks, {}

        def cb(batch: Batch) -> None:
            for task_id in restart_tasks:
                def one(tx, task_id=task_id):
                    t = tx.get(Task, task_id)
                    if t is None or t.desired_state > TaskState.RUNNING:
                        return
                    service = tx.get(Service, t.service_id)
                    if service is None:
                        return
                    node = self.nodes.get(t.node_id)
                    entry = self.global_services.get(t.service_id)
                    if node is None or entry is None:
                        return
                    if node.spec.availability == NodeAvailability.PAUSE or \
                            not constraint_mod.node_matches(
                                entry.constraints, node):
                        t = t.copy()
                        t.desired_state = TaskState.SHUTDOWN
                        tx.update(t)
                        return
                    self.restarts.restart(tx, self.cluster, service, t)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("global restart transaction failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("global restart batch failed")

    # --------------------------------------------------------------- helpers

    def _foreach_task_from_node(self, node: Node, fn) -> None:
        tasks = self.store.view(lambda tx: tx.find(Task, ByNode(node.id)))

        def cb(batch: Batch) -> None:
            for t in tasks:
                if t.service_id in self.global_services:
                    fn(batch, t)

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("global foreachTaskFromNode batch failed")

    def _shutdown_task(self, batch: Batch, t: Task) -> None:
        def one(tx, t=t):
            cur = tx.get(Task, t.id)
            if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                cur = cur.copy()
                cur.desired_state = TaskState.SHUTDOWN
                tx.update(cur)
        try:
            batch.update(one)
        except Exception:
            log.exception("global shutdownTask failed")

    def _shutdown_tasks(self, batch: Batch, tasks: List[Task]) -> None:
        for t in tasks:
            self._shutdown_task(batch, t)

    def _add_task(self, batch: Batch, service: Service,
                  node_id: str) -> None:
        task = common.new_task(self.cluster, service, 0, node_id)

        def one(tx):
            if tx.get(Service, service.id) is None:
                return
            tx.create(task)
        try:
            batch.update(one)
        except Exception:
            log.exception("global addTask failed")

    def _delete_task(self, batch: Batch, t: Task) -> None:
        def one(tx, t=t):
            try:
                tx.delete(Task, t.id)
            except Exception:
                pass
        batch.update(one)

    # -------------------------------------------------------- taskinit hooks

    def is_related_service(self, service: Optional[Service]) -> bool:
        return common.is_global_service(service)

    def slot_tuple(self, t: Task) -> common.SlotTuple:
        return common.SlotTuple(service_id=t.service_id, node_id=t.node_id)

    def fix_task(self, batch: Batch, t: Task) -> None:
        """reference: global.go:174 FixTask."""
        if t.service_id not in self.global_services:
            return
        if t.desired_state > TaskState.RUNNING:
            return
        node = self.nodes.get(t.node_id) if t.node_id else None
        if not t.node_id or common.invalid_node(node):
            self._shutdown_task(batch, t)
            return
        if t.status.state > TaskState.RUNNING:
            self.restart_tasks[t.id] = None
