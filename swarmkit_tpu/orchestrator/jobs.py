"""Jobs orchestrator: run-to-completion replicated and global jobs.

Reference: manager/orchestrator/jobs/{orchestrator.go,replicated/
reconciler.go,global/reconciler.go}.

A shared event-loop orchestrator with one reconciler per job mode.
Replicated jobs fill ``total_completions`` unique slots, at most
``max_concurrent`` in flight; global jobs run one completion per
constraint-matching node per job iteration.  Tasks carry the service's
``job_iteration``; tasks from older iterations are marked REMOVE.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set

from ..models.objects import Cluster, Node, Service, Task
from ..models.types import NodeAvailability, TaskState, Version
from ..scheduler import constraint as constraint_mod
from ..state.events import Event, EventCommit, EventSnapshotRestore
from ..state.store import Batch, ByName, ByService, MemoryStore
from ..state.watch import Closed
from . import common, taskinit
from .replicated import DEFAULT_CLUSTER_NAME
from .restart import Supervisor as RestartSupervisor

log = logging.getLogger("jobs")


class ReplicatedJobReconciler:
    """reference: jobs/replicated/reconciler.go."""

    def __init__(self, store: MemoryStore,
                 restarts: RestartSupervisor):
        self.store = store
        self.restarts = restarts

    def reconcile_service(self, service_id: str,
                          cluster: Optional[Cluster]) -> None:
        def read(tx):
            return (tx.get(Service, service_id),
                    tx.find(Task, ByService(service_id)))

        service, tasks = self.store.view(read)
        if service is None or not common.is_replicated_job(service):
            return
        job_version = (service.job_status.job_iteration.index
                       if service.job_status else 0)
        rj = service.spec.replicated_job
        if rj is None:
            return
        total = rj.total_completions
        max_concurrent = rj.max_concurrent or total

        running = 0
        complete = 0
        restart_tasks: List[str] = []
        remove_tasks: List[str] = []
        slots: Set[int] = set()
        for t in tasks:
            it = t.job_iteration.index if t.job_iteration else 0
            if it == job_version:
                if t.status.state == TaskState.COMPLETE:
                    complete += 1
                    slots.add(t.slot)
                elif t.desired_state <= TaskState.COMPLETE:
                    running += 1
                    slots.add(t.slot)
                    if t.status.state > TaskState.COMPLETE:
                        restart_tasks.append(t.id)
            else:
                if t.status.state <= TaskState.RUNNING and \
                        t.desired_state != TaskState.REMOVE:
                    remove_tasks.append(t.id)

        new_tasks = min(max_concurrent - running,
                        total - complete - running)
        new_tasks = max(new_tasks, 0)

        def cb(batch: Batch) -> None:
            slot = 0
            for _ in range(new_tasks):
                while slot in slots:
                    slot += 1
                slots.add(slot)

                def create(tx, slot=slot):
                    if tx.get(Service, service_id) is None:
                        return
                    task = common.new_task(cluster, service, slot, "")
                    task.job_iteration = Version(index=job_version)
                    task.desired_state = TaskState.COMPLETE
                    tx.create(task)
                batch.update(create)
            for task_id in restart_tasks:
                def restart(tx, task_id=task_id):
                    t = tx.get(Task, task_id)
                    if t is None or t.desired_state > TaskState.COMPLETE:
                        return
                    self.restarts.restart(tx, cluster, service, t)
                batch.update(restart)
            for task_id in remove_tasks:
                def remove(tx, task_id=task_id):
                    t = tx.get(Task, task_id)
                    if t is None or t.desired_state == TaskState.REMOVE:
                        return
                    t = t.copy()
                    t.desired_state = TaskState.REMOVE
                    tx.update(t)
                batch.update(remove)

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("replicated job reconcile failed")


class GlobalJobReconciler:
    """reference: jobs/global/reconciler.go."""

    def __init__(self, store: MemoryStore,
                 restarts: RestartSupervisor):
        self.store = store
        self.restarts = restarts

    def reconcile_service(self, service_id: str,
                          cluster: Optional[Cluster]) -> None:
        def read(tx):
            return (tx.get(Service, service_id),
                    tx.find(Task, ByService(service_id)),
                    tx.find(Node))

        service, tasks, nodes = self.store.view(read)
        if service is None or not common.is_global_job(service):
            return
        job_version = (service.job_status.job_iteration.index
                       if service.job_status else 0)
        constraints = []
        placement = service.spec.task.placement
        if placement and placement.constraints:
            try:
                constraints = constraint_mod.parse(placement.constraints)
            except constraint_mod.InvalidConstraint:
                constraints = []

        covered: Set[str] = set()
        restart_tasks: List[str] = []
        remove_tasks: List[str] = []
        for t in tasks:
            it = t.job_iteration.index if t.job_iteration else 0
            if it != job_version:
                if t.status.state <= TaskState.RUNNING and \
                        t.desired_state != TaskState.REMOVE:
                    remove_tasks.append(t.id)
                continue
            if t.status.state == TaskState.COMPLETE or \
                    t.desired_state <= TaskState.COMPLETE:
                covered.add(t.node_id)
                if TaskState.COMPLETE < t.status.state and \
                        t.desired_state <= TaskState.COMPLETE:
                    restart_tasks.append(t.id)

        def cb(batch: Batch) -> None:
            for node in nodes:
                if node.id in covered:
                    continue
                if common.invalid_node(node) or \
                        node.spec.availability == NodeAvailability.PAUSE:
                    continue
                if not constraint_mod.node_matches(constraints, node):
                    continue

                def create(tx, node_id=node.id):
                    if tx.get(Service, service_id) is None:
                        return
                    task = common.new_task(cluster, service, 0, node_id)
                    task.job_iteration = Version(index=job_version)
                    task.desired_state = TaskState.COMPLETE
                    tx.create(task)
                batch.update(create)
            for task_id in restart_tasks:
                def restart(tx, task_id=task_id):
                    t = tx.get(Task, task_id)
                    if t is None or t.desired_state > TaskState.COMPLETE:
                        return
                    self.restarts.restart(tx, cluster, service, t)
                batch.update(restart)
            for task_id in remove_tasks:
                def remove(tx, task_id=task_id):
                    t = tx.get(Task, task_id)
                    if t is None or t.desired_state == TaskState.REMOVE:
                        return
                    t = t.copy()
                    t.desired_state = TaskState.REMOVE
                    tx.update(t)
                batch.update(remove)

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("global job reconcile failed")


class Orchestrator:
    """reference: jobs/orchestrator.go:34."""

    def __init__(self, store: MemoryStore,
                 restarts: Optional[RestartSupervisor] = None):
        self.store = store
        self.restarts = restarts or RestartSupervisor(store)
        self.replicated = ReplicatedJobReconciler(store, self.restarts)
        self.global_ = GlobalJobReconciler(store, self.restarts)
        self.cluster: Optional[Cluster] = None
        self._dirty: Set[str] = set()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="jobs",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)
        self.restarts.cancel_all()

    def run(self) -> None:
        try:
            def init(tx):
                for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                    self.cluster = c
                for s in tx.find(Service):
                    if common.is_replicated_job(s) or common.is_global_job(s):
                        self._dirty.add(s.id)

            # accepts_blocks: a job task's ASSIGNED flip changes neither
            # the desired-state running count nor completions, so
            # assignment blocks need no reconcile
            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            try:
                taskinit.check_tasks(self.store, self.store.view(), self,
                                     self.restarts)
                self._tick()
                while not self._stop.is_set():
                    try:
                        event = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(event, EventCommit):
                        self._tick()
                    elif isinstance(event, EventSnapshotRestore):
                        self._resync()
                    elif isinstance(event, Event):
                        self._handle_event(event)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _resync(self) -> None:
        self._dirty.clear()

        def init(tx):
            for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                self.cluster = c
            for s in tx.find(Service):
                if common.is_replicated_job(s) or common.is_global_job(s):
                    self._dirty.add(s.id)

        self.store.view(init)
        self._tick()

    def _handle_event(self, ev: Event) -> None:
        obj = ev.obj
        if isinstance(obj, Cluster):
            if ev.action != "delete":
                self.cluster = obj
        elif isinstance(obj, Service):
            if not (common.is_replicated_job(obj)
                    or common.is_global_job(obj)):
                return
            if ev.action == "delete":
                common.set_service_tasks_remove(self.store, obj)
                self.restarts.clear_service_history(obj.id)
                self._dirty.discard(obj.id)
            else:
                self._dirty.add(obj.id)
        elif isinstance(obj, Task):
            if obj.service_id and ev.action in ("update", "delete"):
                service = self.store.raw_get(Service, obj.service_id)
                if common.is_replicated_job(service) or \
                        common.is_global_job(service):
                    self._dirty.add(obj.service_id)
        elif isinstance(obj, Node) and ev.action in ("create", "update"):
            # a new/recovered node may need global-job tasks
            for s in self.store.view(lambda tx: tx.find(Service)):
                if common.is_global_job(s):
                    self._dirty.add(s.id)

    def _tick(self) -> None:
        if not self._dirty:
            return
        dirty, self._dirty = self._dirty, set()
        for service_id in dirty:
            service = self.store.raw_get(Service, service_id)
            if service is None:
                continue
            if common.is_replicated_job(service):
                self.replicated.reconcile_service(service_id, self.cluster)
            elif common.is_global_job(service):
                self.global_.reconcile_service(service_id, self.cluster)

    # -------------------------------------------------------- taskinit hooks

    def is_related_service(self, service: Optional[Service]) -> bool:
        return common.is_replicated_job(service) or \
            common.is_global_job(service)

    def slot_tuple(self, t: Task) -> common.SlotTuple:
        if t.slot:
            return common.SlotTuple(service_id=t.service_id, slot=t.slot)
        return common.SlotTuple(service_id=t.service_id, node_id=t.node_id)

    def fix_task(self, batch: Batch, t: Task) -> None:
        if t.service_id:
            self._dirty.add(t.service_id)
