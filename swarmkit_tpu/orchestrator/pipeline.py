"""Pipeline workflows: DAG-gated rollout across services.

A service naming upstream services in ``ServiceSpec.depends_on``
(validated acyclic by the control API) is a *pipeline stage*: the
scheduler's gate (scheduler/gang.py ``pipeline_gate``) holds its tasks
back until this supervisor **releases** the stage — every upstream is
ready (replicated services: RUNNING count >= desired replicas; jobs:
completions reached; global: at least one task RUNNING).  Release is
*sticky*: later upstream churn (restarts, node loss) never re-gates a
stage that already started, so steady-state convergence is monotone.

Failure cascades: an upstream observed *poisoned* (``POISON_FAILURES``
cumulative task failures) — or itself halted — **halts** every
downstream stage.  A halted stage's pending tasks defer at the gate
with the halt reason; ``ServiceSpec.on_upstream_failure ==
"rollback"`` additionally scales the stage to zero replicas so its
running tasks drain.  Halt verdicts are sticky: the operator re-arms
a halted stage with controlapi ``resume_pipeline`` after fixing the
poison — the resume stamps a ``resumed_at`` watermark that forgives
every failure observed at/before it (replicated ledger cleared in the
resume transaction, leader-local ledgers dropped on seeing the fresh
stamp, pre-watermark failed task rows skipped on re-scan).

The loop is the established threadless-drivable FSM shape
(orchestrator/autoscaler.py, update.py): production wraps one thread
(``start_worker=True``); the simulator constructs
``start_worker=False`` and pumps ``drive()`` from the leader's control
step under virtual time.  Verdicts write ``Service.pipeline_status``
(models/objects.py) through ``store.update`` — epoch-pinned at commit,
replicated with the row — so a successor leader's supervisor resumes
released/halted stages exactly where the deposed one left them.
Failure OBSERVATIONS replicate too: every drive folds newly seen
distinct failed-task ids into ``PipelineStatus.failed_ids`` on the
committed row, so a poison count at 2/3 on a crashed leader trips on
the successor's first new observation instead of restarting at zero.

``_cascade_enabled`` is the checker-sensitivity seam: with it off a
poisoned upstream no longer halts downstream stages and the sim's
``pipeline-chaos`` scenario expectations must catch the miss.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..models.objects import PipelineStatus, Service, Task
from ..models.specs import ServiceMode
from ..models.types import TaskState, now
from ..state.store import MemoryStore, WriteTx
from ..utils.metrics import registry as _metrics

log = logging.getLogger("pipeline")

#: cumulative task failures observed on one service before the
#: supervisor declares it poisoned and halts its downstream stages
POISON_FAILURES = 3


class PipelineSupervisor:
    """One release/halt decision pass per ``drive()`` over every
    service that names upstream dependencies."""

    #: checker-sensitivity seam (tests/test_gang.py): False disables
    #: the failure cascade — a poisoned upstream then never halts its
    #: downstream stages and the chaos expectations must fire.
    _cascade_enabled = True

    def __init__(self, store: MemoryStore, start_worker: bool = True,
                 interval: float = 2.0):
        self.store = store
        self.interval = interval
        self.threadless = not start_worker
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: leader-local failure observation: service id -> task ids
        #: seen FAILED/REJECTED at least once (cumulative — a restarted
        #: slot failing again is a NEW task id, so flapping accrues)
        self._failed_seen: Dict[str, Set[str]] = {}
        #: last ``resumed_at`` watermark acted on per service — a fresh
        #: stamp (operator resume_pipeline) drops local observations
        self._resume_seen: Dict[str, float] = {}
        self.stats = {"released": 0, "halted": 0, "rollbacks": 0}

    # --------------------------------------------------------------- running

    def start(self) -> None:
        """Production mode: one daemon thread, drive every interval."""
        if self.threadless or (self._thread is not None
                               and self._thread.is_alive()):
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.drive()
                except Exception:
                    log.exception("pipeline pass failed")

        self._thread = threading.Thread(target=loop, name="pipeline",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Teardown without store writes (deposed-leader discipline)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ---------------------------------------------------------------- drive

    def drive(self) -> None:
        """One synchronous decision pass.  Threadless mode re-raises
        store failures (leadership loss) to the caller — the sim's
        control step handles the deposal, exactly like the update,
        restart, and autoscale supervisors."""
        services, tasks = self.store.view(
            lambda tx: (tx.find(Service), tx.find(Task)))
        stages = [s for s in services if s.spec.depends_on]
        if not stages:
            return
        by_name: Dict[str, Service] = {
            s.spec.annotations.name: s for s in services}
        by_service: Dict[str, List[Task]] = {}
        for t in tasks:
            by_service.setdefault(t.service_id, []).append(t)
        # only pipeline participants (stages + their upstreams) carry
        # replicated observations — an unrelated service's failures are
        # the restart supervisor's business, not a pipeline verdict
        dep_names = {d for s in stages for d in s.spec.depends_on}
        relevant = {s.id for s in stages} | {
            s.id for s in services
            if s.spec.annotations.name in dep_names}
        poisoned = self._observe_failures(services, by_service,
                                          relevant)

        for svc in sorted(stages, key=lambda s: s.id):
            try:
                self._drive_stage(svc, by_name, by_service, poisoned)
            except Exception:
                if self.threadless:
                    raise
                log.exception("pipeline decision for %s failed", svc.id)

    def _observe_failures(self, services, by_service,
                          relevant: Set[str]) -> Set[str]:
        """Accumulate per-service failure observations; returns the ids
        of services currently over the poison threshold.  Observations
        for pipeline participants (``relevant``) merge with — and fold
        back into — the replicated ``PipelineStatus.failed_ids`` so
        the count survives leader failover."""
        poisoned: Set[str] = set()
        for svc in services:
            seen = self._failed_seen.setdefault(svc.id, set())
            st = svc.pipeline_status
            watermark = st.resumed_at if st is not None else 0.0
            if watermark and self._resume_seen.get(svc.id) != watermark:
                # operator resume: observations predating the stamp are
                # forgiven — drop the local ledger (the replicated one
                # was cleared in the resume transaction)
                self._resume_seen[svc.id] = watermark
                seen.clear()
            if st is not None and st.failed_ids:
                # a prior leader's (or our own committed) observations
                seen.update(st.failed_ids)
            for t in by_service.get(svc.id, []):
                if t.status.state in (TaskState.FAILED,
                                      TaskState.REJECTED) \
                        and t.status.timestamp > watermark:
                    seen.add(t.id)
            if svc.id in relevant:
                have = set(st.failed_ids) if st is not None else set()
                if seen - have:
                    self._persist_failures(svc.id, set(seen))
            if len(seen) >= POISON_FAILURES:
                poisoned.add(svc.id)
        return poisoned

    @staticmethod
    def _upstream_ready(svc: Service, tasks: List[Task]) -> bool:
        """Readiness bar for releasing a downstream stage."""
        running = sum(1 for t in tasks
                      if t.status.state == TaskState.RUNNING
                      and t.desired_state <= TaskState.RUNNING)
        mode = svc.spec.mode
        if mode == ServiceMode.REPLICATED:
            want = svc.spec.replicated.replicas \
                if svc.spec.replicated else 1
            return running >= want
        if mode == ServiceMode.REPLICATED_JOB:
            done = sum(1 for t in tasks
                       if t.status.state == TaskState.COMPLETE)
            want = svc.spec.replicated_job.total_completions \
                if svc.spec.replicated_job else 1
            return done >= want
        if mode == ServiceMode.GLOBAL_JOB:
            return any(t.status.state == TaskState.COMPLETE
                       for t in tasks)
        return running >= 1    # GLOBAL: at least one member up

    def _drive_stage(self, svc: Service, by_name, by_service,
                     poisoned: Set[str]) -> None:
        st = svc.pipeline_status or PipelineStatus()
        if st.state == "halted":
            return    # sticky: operator action restarts the pipeline

        # upstream survey: any poisoned/halted upstream cascades; all
        # ready (and none missing) releases
        halt_reason: Optional[str] = None
        all_ready = True
        for dep in svc.spec.depends_on:
            up = by_name.get(dep)
            if up is None:
                all_ready = False    # forward reference: stay gated
                continue
            up_st = up.pipeline_status
            if self._cascade_enabled and up_st is not None \
                    and up_st.state == "halted":
                halt_reason = f'upstream "{dep}" halted'
                break
            if self._cascade_enabled and up.id in poisoned:
                halt_reason = (f'upstream "{dep}" poisoned '
                               f'({POISON_FAILURES} task failures)')
                break
            if not self._upstream_ready(up, by_service.get(up.id, [])):
                all_ready = False

        if halt_reason is not None:
            self._halt(svc, halt_reason)
            return
        if st.state == "released":
            return    # sticky: upstream churn never re-gates a stage
        if all_ready:
            self._release(svc)

    # ---------------------------------------------------------------- writes

    def _persist_failures(self, sid: str, seen: Set[str]) -> None:
        """Fold newly observed distinct failed-task ids into the
        replicated row (ISSUE 16 residual: the poison threshold must
        trip across a leader crash at 2/3 observations)."""

        def cb(tx: WriteTx) -> None:
            cur = tx.get(Service, sid)
            if cur is None:
                return
            st = cur.pipeline_status
            have = set(st.failed_ids) if st is not None else set()
            merged = sorted(have | seen)
            if st is not None and merged == sorted(st.failed_ids):
                return    # raced with our own earlier commit: no-op
            cur = cur.copy()
            cur.pipeline_status = (cur.pipeline_status.copy()
                                   if cur.pipeline_status is not None
                                   else PipelineStatus())
            cur.pipeline_status.failed_ids = merged
            tx.update(cur)

        self._update(cb, "persist pipeline failure observations")

    def _release(self, svc: Service) -> None:
        sid = svc.id
        state: Dict[str, bool] = {}

        def cb(tx: WriteTx) -> None:
            cur = tx.get(Service, sid)
            if cur is None or not cur.spec.depends_on:
                return
            cur_st = cur.pipeline_status
            if cur_st is not None and cur_st.state != "waiting":
                return    # released already, or halted meanwhile
            cur = cur.copy()
            cur.pipeline_status = PipelineStatus(
                state="released", reason="", updated_at=now(),
                failed_ids=list(cur_st.failed_ids) if cur_st else [],
                resumed_at=cur_st.resumed_at if cur_st else 0.0)
            tx.update(cur)
            state["written"] = True

        self._update(cb, "release pipeline stage")
        if not state.get("written"):
            return
        self.stats["released"] += 1
        _metrics.counter(f'swarm_pipeline_released{{service="{sid}"}}')
        log.info("pipeline stage %s released", sid)

    def _halt(self, svc: Service, reason: str) -> None:
        sid = svc.id
        rollback = svc.spec.on_upstream_failure == "rollback"
        state: Dict[str, bool] = {}

        def cb(tx: WriteTx) -> None:
            cur = tx.get(Service, sid)
            if cur is None or not cur.spec.depends_on:
                return
            cur_st = cur.pipeline_status
            if cur_st is not None and cur_st.state == "halted":
                return
            cur = cur.copy()
            cur.pipeline_status = PipelineStatus(
                state="halted", reason=reason, updated_at=now(),
                failed_ids=list(cur_st.failed_ids) if cur_st else [],
                resumed_at=cur_st.resumed_at if cur_st else 0.0)
            if rollback and cur.spec.replicated is not None:
                # rollback policy: drain the stage — the orchestrator
                # shuts the running tasks down as replicas go to zero
                cur.spec.replicated.replicas = 0
            tx.update(cur)
            state["written"] = True

        self._update(cb, "halt pipeline stage")
        if not state.get("written"):
            return
        self.stats["halted"] += 1
        if rollback:
            self.stats["rollbacks"] += 1
        _metrics.counter(f'swarm_pipeline_halted{{service="{sid}"}}')
        log.warning("pipeline stage %s halted: %s%s", sid, reason,
                    " (rolled back to 0 replicas)" if rollback else "")

    def _update(self, cb, what: str) -> None:
        try:
            self.store.update(cb)
        except Exception:
            if self.threadless:
                raise   # sim: leadership loss must reach the control step
            log.exception("failed to %s", what)
