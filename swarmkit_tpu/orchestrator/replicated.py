"""Replicated-service orchestrator: slot-count reconciliation.

Reference: manager/orchestrator/replicated/{replicated,services,tasks,slot}.go.

Event-loop object: collects dirty services and restart-candidate tasks from
store events, acts on commit boundaries.  Scale-up creates tasks in missing
slots; scale-down prefers slots on the most-crowded nodes (and non-running
tasks first) and marks the rest desired-REMOVE for the agent to stop and the
task reaper to delete.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..models.objects import Cluster, Node, Service, Task
from ..models.types import TaskState
from ..obs.trace import tracer
from ..state.events import Event, EventCommit, EventSnapshotRestore
from ..state.store import Batch, ByName, ByNode, ByService, MemoryStore
from ..state.watch import Closed
from ..utils.metrics import registry as _metrics
from . import common
from .restart import Supervisor as RestartSupervisor
from .update import Supervisor as UpdateSupervisor
from . import taskinit

log = logging.getLogger("replicated")

DEFAULT_CLUSTER_NAME = "default"  # reference: store.DefaultClusterName

# cached Timer reference (Registry.reset() resets in place)
_RECONCILE_TIMER = _metrics.timer(
    'swarm_orchestrator_reconcile{kind="replicated"}')


class Orchestrator:
    def __init__(self, store: MemoryStore,
                 restarts: Optional[RestartSupervisor] = None,
                 updater: Optional[UpdateSupervisor] = None):
        self.store = store
        self.restarts = restarts or RestartSupervisor(store)
        self.updater = updater or UpdateSupervisor(store, self.restarts)
        self.cluster: Optional[Cluster] = None
        self.reconcile_services: Dict[str, Service] = {}
        self.restart_tasks: Dict[str, None] = {}   # insertion-ordered set
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="replicated",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)
        self.updater.cancel_all()
        self.restarts.cancel_all()

    def run(self) -> None:
        try:
            def init(tx):
                for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                    self.cluster = c
                for s in tx.find(Service):
                    if common.is_replicated_service(s):
                        self.reconcile_services[s.id] = s

            # accepts_blocks: scheduler assignment blocks carry
            # state<=RUNNING transitions by store contract — never a
            # failure this loop reacts to (_handle_task_change fires on
            # state>RUNNING); node invalidation arrives as Node events
            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            try:
                # outside view_and_watch: check_tasks writes through
                # store.batch, which needs the update lock view_and_watch
                # holds; the events it causes replay through sub (idempotent)
                taskinit.check_tasks(self.store, self.store.view(), self,
                                     self.restarts)
                self._tick()
                while not self._stop.is_set():
                    try:
                        event = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(event, EventCommit):
                        self._tick()
                    elif isinstance(event, EventSnapshotRestore):
                        self._resync()
                    elif isinstance(event, Event):
                        self._handle_event(event)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _resync(self) -> None:
        self.reconcile_services.clear()
        self.restart_tasks.clear()

        def init(tx):
            for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                self.cluster = c
            for s in tx.find(Service):
                if common.is_replicated_service(s):
                    self.reconcile_services[s.id] = s

        self.store.view(init)
        self._tick()

    # ----------------------------------------------------------- event intake

    def _handle_event(self, ev: Event) -> None:
        obj = ev.obj
        if isinstance(obj, Service):
            if not common.is_replicated_service(obj):
                return
            if ev.action == "delete":
                common.set_service_tasks_remove(self.store, obj)
                self.restarts.clear_service_history(obj.id)
                self.reconcile_services.pop(obj.id, None)
            else:
                self.reconcile_services[obj.id] = obj
        elif isinstance(obj, Task):
            if ev.action == "delete":
                if obj.desired_state <= TaskState.RUNNING and obj.service_id:
                    service = self.store.raw_get(Service, obj.service_id)
                    if common.is_replicated_service(service):
                        self.reconcile_services[service.id] = service
                self.restarts.cancel(obj.id)
            else:
                self._handle_task_change(obj)
        elif isinstance(obj, Node):
            if ev.action == "delete":
                self._restart_tasks_by_node(obj.id)
            else:
                if common.invalid_node(obj):
                    self._restart_tasks_by_node(obj.id)
        elif isinstance(obj, Cluster):
            if ev.action != "delete":
                self.cluster = obj

    def _handle_task_change(self, t: Task) -> None:
        """A task changed (usually agent status): queue restart if it died
        or its node became invalid (reference: tasks.go:120)."""
        if t.desired_state > TaskState.RUNNING:
            # a PREEMPTED task (scheduler marked it desired-SHUTDOWN to
            # make room for a higher-priority band) empties its slot
            # outside every other trigger — reconcile the service so the
            # slot requeues at its own priority
            if "swarm.preempted.at" in t.annotations.labels \
                    and t.service_id:
                service = self.store.raw_get(Service, t.service_id)
                if common.is_replicated_service(service):
                    self.reconcile_services[service.id] = service
            return
        n = self.store.raw_get(Node, t.node_id) if t.node_id else None
        service = self.store.raw_get(Service, t.service_id) \
            if t.service_id else None
        if not common.is_replicated_service(service):
            return
        if t.status.state > TaskState.RUNNING or \
                (t.node_id and common.invalid_node(n)):
            self.restart_tasks[t.id] = None

    def _restart_tasks_by_node(self, node_id: str) -> None:
        for t in self.store.view(
                lambda tx: tx.find(Task, ByNode(node_id))):
            if t.desired_state > TaskState.RUNNING:
                continue
            service = self.store.raw_get(Service, t.service_id)
            if common.is_replicated_service(service):
                self.restart_tasks[t.id] = None

    # ----------------------------------------------------------------- ticks

    def _tick(self) -> None:
        # task-level first, so restarts respond before reconciliation
        self._tick_tasks()
        self._tick_services()

    def _tick_tasks(self) -> None:
        if not self.restart_tasks:
            return
        restart_tasks, self.restart_tasks = self.restart_tasks, {}

        def cb(batch: Batch) -> None:
            for task_id in restart_tasks:
                def one(tx, task_id=task_id):
                    t = tx.get(Task, task_id)
                    if t is None or t.desired_state > TaskState.RUNNING:
                        return
                    service = tx.get(Service, t.service_id)
                    if not common.is_replicated_service(service):
                        return
                    self.restarts.restart(tx, self.cluster, service, t)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("task restart transaction failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("task restart batch failed")

    def _tick_services(self) -> None:
        if not self.reconcile_services:
            return
        services, self.reconcile_services = self.reconcile_services, {}
        with tracer.span("orchestrator.reconcile", "orchestrator",
                         kind="replicated", services=len(services)):
            with _RECONCILE_TIMER.time():
                for s in services.values():
                    self._reconcile(s)

    # ------------------------------------------------------------- reconcile

    def _updatable_and_dead_slots(self, service: Service):
        """reference: slot.go:75 updatableAndDeadSlots."""
        tasks = self.store.view(
            lambda tx: tx.find(Task, ByService(service.id)))
        slots: Dict[int, List[Task]] = {}
        for t in tasks:
            slots.setdefault(t.slot, []).append(t)
        updatable: Dict[int, List[Task]] = {}
        dead: Dict[int, List[Task]] = {}
        for slot_id, slot in slots.items():
            u = self.restarts.updatable_tasks_in_slot(slot, service)
            if u:
                updatable[slot_id] = u
            else:
                dead[slot_id] = slot
        return updatable, dead

    def _reconcile(self, service: Service) -> None:
        """reference: services.go:95 reconcile."""
        cur = self.store.raw_get(Service, service.id)
        if cur is None:
            return
        service = cur
        running_slots, dead_slots = self._updatable_and_dead_slots(service)
        num_slots = len(running_slots)
        slots_slice = list(running_slots.values())
        specified = service.spec.replicated.replicas \
            if service.spec.replicated else 0

        if specified > num_slots:
            self.updater.update(self.cluster, service, slots_slice)

            def cb(batch: Batch) -> None:
                self._add_tasks(batch, service, running_slots, dead_slots,
                                specified - num_slots)
                self._delete_tasks(batch, dead_slots)

            self._safe_batch(cb)
        elif specified < num_slots:
            # running slots sort first (removal takes from the end, so
            # non-running tasks are preferentially removed); lower slot
            # numbers first on ties (reference: slot.go:20 Less)
            slots_slice.sort(key=lambda slot: (
                0 if any(t.status.state == TaskState.RUNNING for t in slot)
                else 1,
                slot[0].slot))
            # nth-copy-per-node index (1, 2, 3...) — remove highest first
            slots_by_node: Dict[str, int] = {}
            with_indices: List[Tuple[int, List[Task]]] = []
            for slot in slots_slice:
                if len(slot) == 1 and slot[0].node_id:
                    slots_by_node[slot[0].node_id] = \
                        slots_by_node.get(slot[0].node_id, 0) + 1
                    with_indices.append((slots_by_node[slot[0].node_id],
                                         slot))
                else:
                    with_indices.append((-1, slot))
            with_indices.sort(key=lambda p: (p[0] < 0, p[0]))
            sorted_slots = [slot for _, slot in with_indices]

            self.updater.update(self.cluster, service,
                                sorted_slots[:specified])

            def cb(batch: Batch) -> None:
                self._delete_tasks(batch, dead_slots)
                self._set_desired_state(batch, sorted_slots[specified:],
                                        TaskState.REMOVE)

            self._safe_batch(cb)
        else:
            def cb(batch: Batch) -> None:
                self._delete_tasks(batch, dead_slots)

            self._safe_batch(cb)
            self.updater.update(self.cluster, service, slots_slice)

    def _add_tasks(self, batch: Batch, service: Service,
                   running_slots: Dict[int, List[Task]],
                   dead_slots: Dict[int, List[Task]], count: int) -> None:
        slot = 0
        for _ in range(count):
            while True:
                slot += 1
                if slot not in running_slots:
                    break
            dead_slots.pop(slot, None)

            def one(tx, slot=slot):
                tx.create(common.new_task(self.cluster, service, slot, ""))
            try:
                batch.update(one)
            except Exception:
                log.exception("failed to create task")

    def _set_desired_state(self, batch: Batch, slots: List[List[Task]],
                           state: TaskState) -> None:
        for slot in slots:
            for t in slot:
                def one(tx, t=t):
                    cur = tx.get(Task, t.id)
                    if cur is None:
                        return
                    if cur.desired_state > state:
                        # time travel is not allowed
                        return
                    cur = cur.copy()
                    cur.desired_state = state
                    tx.update(cur)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("failed to update desired state")

    def _delete_tasks(self, batch: Batch,
                      slots: Dict[int, List[Task]]) -> None:
        for slot in slots.values():
            for t in slot:
                def one(tx, t=t):
                    try:
                        tx.delete(Task, t.id)
                    except Exception:
                        pass
                batch.update(one)

    def _safe_batch(self, cb) -> None:
        try:
            self.store.batch(cb)
        except Exception:
            log.exception("reconcile batch failed")

    # -------------------------------------------------------- taskinit hooks

    def is_related_service(self, service: Optional[Service]) -> bool:
        return common.is_replicated_service(service)

    def slot_tuple(self, t: Task) -> common.SlotTuple:
        return common.SlotTuple(service_id=t.service_id, slot=t.slot)

    def fix_task(self, batch: Batch, t: Task) -> None:
        """reference: tasks.go:157 FixTask."""
        if t.desired_state > TaskState.RUNNING:
            return
        n = self.store.raw_get(Node, t.node_id) if t.node_id else None
        service = self.store.raw_get(Service, t.service_id)
        if not common.is_replicated_service(service):
            return
        if t.status.state > TaskState.RUNNING or \
                (t.node_id and common.invalid_node(n)):
            self.restart_tasks[t.id] = None
