"""Restart supervisor: replaces failed/stopped tasks under the service's
restart policy, with delayed starts and per-slot restart history.

Reference: manager/orchestrator/restart/restart.go.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..models.objects import Cluster, Node, Service, Task
from ..models.types import (
    NodeAvailability, NodeState, RestartCondition, TaskState, now,
)
from ..state.events import Event, match
from ..state.store import MemoryStore, WriteTx
from . import common

log = logging.getLogger("restart")

DEFAULT_OLD_TASK_TIMEOUT = 60.0  # reference: restart.go:20


@dataclass
class _RestartInfo:
    total_restarts: int = 0
    restarted_instances: List[float] = field(default_factory=list)
    spec_version: int = 0


class _DelayedStart:
    def __init__(self) -> None:
        self.cancelled = threading.Event()
        self.done = threading.Event()
        self.waiter = False


class Supervisor:
    def __init__(self, store: MemoryStore):
        self.store = store
        self._mu = threading.Lock()
        self._delays: Dict[str, _DelayedStart] = {}
        self._history: Dict[str, Dict[common.SlotTuple, _RestartInfo]] = {}
        self.task_timeout = DEFAULT_OLD_TASK_TIMEOUT

    # ------------------------------------------------------------ restarting

    def restart(self, tx: WriteTx, cluster: Optional[Cluster],
                service: Service, t: Task) -> None:
        """Shut down t and create a replacement if policy allows.

        Must be called inside a store.update transaction (reference:
        restart.go:117 Restart).
        """
        with self._mu:
            old_delay = self._delays.get(t.id)
            if old_delay is not None:
                if not old_delay.waiter:
                    old_delay.waiter = True
                    threading.Thread(
                        target=self._wait_restart,
                        args=(old_delay, cluster, t.id),
                        daemon=True).start()
                return

        if t.desired_state > TaskState.COMPLETE:
            raise RuntimeError(
                "restart called on task that was already shut down")

        t = t.copy()
        t.desired_state = TaskState.SHUTDOWN
        tx.update(t)

        if not self._should_restart(t, service):
            return

        if common.is_replicated_service(service) \
                or common.is_replicated_job(service):
            restart_task = common.new_task(cluster, service, t.slot, "")
        elif common.is_global_service(service) \
                or common.is_global_job(service):
            restart_task = common.new_task(cluster, service, 0, t.node_id)
        else:
            log.error("service not supported by restart supervisor")
            return

        if common.is_replicated_job(service) or common.is_global_job(service):
            from ..models.types import Version
            restart_task.job_iteration = Version(
                service.job_status.job_iteration.index
                if service.job_status else 0)

        n = tx.get(Node, t.node_id) if t.node_id else None

        restart_task.desired_state = TaskState.READY

        restart_delay = 0.0
        # restart delay is not applied on drained nodes
        if n is None or n.spec.availability != NodeAvailability.DRAIN:
            if t.spec.restart is not None:
                restart_delay = t.spec.restart.delay
            else:
                restart_delay = common.DEFAULT_RESTART_DELAY

        # normally wait for the old task to stop running; skip if it's
        # already dead or its node is down
        wait_stop = not ((n is not None
                          and n.status.state == NodeState.DOWN)
                         or t.status.state > TaskState.RUNNING)

        tx.create(restart_task)

        tuple_ = common.SlotTuple(
            service_id=restart_task.service_id, slot=restart_task.slot,
            node_id=restart_task.node_id if not restart_task.slot else "")
        self.record_restart_history(tuple_, restart_task)
        self.delay_start(t, restart_task.id, restart_delay, wait_stop)

    def _wait_restart(self, old_delay: _DelayedStart,
                      cluster: Optional[Cluster], task_id: str) -> None:
        old_delay.done.wait()

        def cb(tx: WriteTx) -> None:
            t = tx.get(Task, task_id)
            if t is None or t.desired_state > TaskState.RUNNING:
                return
            service = tx.get(Service, t.service_id)
            if service is None:
                return
            self.restart(tx, cluster, service, t)

        try:
            self.store.update(cb)
        except Exception:
            log.exception("failed to restart task after waiting for "
                          "previous restart")

    # -------------------------------------------------------------- policy

    def _should_restart(self, t: Task, service: Service) -> bool:
        condition = common.restart_condition(t)
        if condition == RestartCondition.ANY:
            if (common.is_replicated_job(service)
                    or common.is_global_job(service)) \
                    and t.status.state == TaskState.COMPLETE:
                return False
        elif condition == RestartCondition.ON_FAILURE:
            if t.status.state == TaskState.COMPLETE:
                return False
        else:  # NONE
            return False

        if t.spec.restart is None or t.spec.restart.max_attempts == 0:
            return True

        tuple_ = common.SlotTuple(service_id=t.service_id, slot=t.slot)
        if common.is_global_service(service):
            tuple_ = common.SlotTuple(service_id=t.service_id,
                                      node_id=t.node_id)

        with self._mu:
            info = self._history.get(t.service_id, {}).get(tuple_)
            if info is None or (t.spec_version is not None
                                and t.spec_version.index != info.spec_version):
                return True

            max_attempts = t.spec.restart.max_attempts
            window = t.spec.restart.window
            if not window:
                return info.total_restarts < max_attempts

            if not info.restarted_instances:
                return True

            timestamp = t.status.applied_at or t.status.timestamp or now()
            lookback = timestamp - window

            # drop restarts before the lookback window
            instances = [s for s in info.restarted_instances if s > lookback]
            info.restarted_instances = instances
            # ignore restarts that happened after this task's timestamp
            num = sum(1 for s in instances if s < timestamp)
            return num < max_attempts

    def updatable_tasks_in_slot(self, slot: common.Slot,
                                service: Service) -> common.Slot:
        """reference: restart.go:333 UpdatableTasksInSlot."""
        if not slot:
            return []
        updatable = [t for t in slot if t.desired_state <= TaskState.RUNNING]
        if updatable:
            return updatable
        from ..models.types import UpdateState
        if service.update_status is not None and \
                service.update_status.state == UpdateState.ROLLBACK_STARTED:
            return []
        newest = max(slot, key=common.task_timestamp)
        if not self._should_restart(newest, service):
            return [newest]
        return []

    def record_restart_history(self, tuple_: common.SlotTuple,
                               replacement: Task) -> None:
        if replacement.spec.restart is None \
                or replacement.spec.restart.max_attempts == 0:
            return
        with self._mu:
            per_service = self._history.setdefault(
                replacement.service_id, {})
            info = per_service.setdefault(tuple_, _RestartInfo())
            if replacement.spec_version is not None and \
                    replacement.spec_version.index != info.spec_version:
                info.total_restarts = 0
                info.restarted_instances = []
                info.spec_version = replacement.spec_version.index
            info.total_restarts += 1
            if replacement.spec.restart.window:
                info.restarted_instances.append(
                    replacement.meta.created_at or now())

    # -------------------------------------------------------- delayed starts

    def delay_start(self, old_task: Optional[Task], new_task_id: str,
                    delay: float, wait_stop: bool) -> threading.Event:
        """Move new_task READY->RUNNING after the delay elapses and the old
        task stops (or times out).  Returns the completion event
        (reference: restart.go:427 DelayStart)."""
        ds = _DelayedStart()
        with self._mu:
            while True:
                old = self._delays.get(new_task_id)
                if old is None:
                    break
                old.cancelled.set()
                self._mu.release()
                old.done.wait(timeout=5)
                self._mu.acquire()
                if self._delays.get(new_task_id) is old:
                    del self._delays[new_task_id]
            self._delays[new_task_id] = ds

        wait_for_task = (wait_stop and old_task is not None
                         and old_task.status.state <= TaskState.RUNNING)

        sub = None
        if wait_for_task:
            old_id = old_task.id
            old_node = old_task.node_id

            def pred(ev):
                if not isinstance(ev, Event):
                    return False
                obj = ev.obj
                if isinstance(obj, Task) and obj.id == old_id \
                        and ev.action == "update" \
                        and obj.status.state > TaskState.RUNNING:
                    return True
                if isinstance(obj, Node) and obj.id == old_node:
                    if ev.action == "delete":
                        return True
                    if ev.action == "update" \
                            and obj.status.state == NodeState.DOWN:
                        return True
                return False

            sub = self.store.queue.subscribe(pred)

        threading.Thread(target=self._delayed_start_thread,
                         args=(ds, sub, new_task_id, delay, wait_for_task),
                         daemon=True).start()
        return ds.done

    def _delayed_start_thread(self, ds: _DelayedStart, sub,
                              new_task_id: str, delay: float,
                              wait_for_task: bool) -> None:
        try:
            # 1. wait out the restart delay (interruptible by cancel)
            if ds.cancelled.wait(timeout=delay):
                return
            # 2. wait for the old task to stop (bounded by task_timeout)
            if wait_for_task and sub is not None:
                deadline = now() + self.task_timeout
                while not ds.cancelled.is_set():
                    remaining = deadline - now()
                    if remaining <= 0:
                        break
                    try:
                        sub.get(timeout=min(remaining, 0.5))
                        break
                    except TimeoutError:
                        continue
                    except Exception:
                        break
            if ds.cancelled.is_set():
                return
            try:
                self.start_now(new_task_id)
            except Exception:
                log.exception("moving task to RUNNING failed")
        finally:
            if sub is not None:
                self.store.queue.unsubscribe(sub)
            with self._mu:
                if self._delays.get(new_task_id) is ds:
                    del self._delays[new_task_id]
            ds.done.set()

    def start_now(self, task_id: str) -> None:
        """Moves the task to the RUNNING state (reference: StartNow)."""

        def cb(tx: WriteTx) -> None:
            t = tx.get(Task, task_id)
            if t is None or t.desired_state >= TaskState.RUNNING:
                return
            t = t.copy()
            t.desired_state = TaskState.RUNNING
            tx.update(t)

        self.store.update(cb)

    def cancel(self, task_id: str) -> None:
        with self._mu:
            ds = self._delays.get(task_id)
        if ds is not None:
            ds.cancelled.set()
            ds.done.wait(timeout=5)

    def cancel_all(self) -> None:
        with self._mu:
            delays = list(self._delays.values())
        for ds in delays:
            ds.cancelled.set()

    def clear_service_history(self, service_id: str) -> None:
        with self._mu:
            self._history.pop(service_id, None)
