"""Restart supervisor: replaces failed/stopped tasks under the service's
restart policy, with delayed starts and per-slot restart history.

Reference: manager/orchestrator/restart/restart.go.

Design difference from the reference: the reference spawns one goroutine per
delayed start (cheap in Go); here a restart storm would mean thousands of
Python threads, so all delayed starts are driven by a **single timer worker**
holding a deadline heap plus one store subscription that watches for the
old-task-stopped / node-down conditions.
"""

from __future__ import annotations

import heapq
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..models.objects import Cluster, Node, Service, Task
from ..models.types import (
    NodeAvailability, NodeState, RestartCondition, TaskState, now,
)
from ..state.events import Event
from ..state.store import MemoryStore, WriteTx
from . import common

log = logging.getLogger("restart")

DEFAULT_OLD_TASK_TIMEOUT = 60.0  # reference: restart.go:20


@dataclass
class _RestartInfo:
    total_restarts: int = 0
    restarted_instances: List[float] = field(default_factory=list)
    spec_version: int = 0


class _DelayedStart:
    """One pending READY->RUNNING transition, owned by the timer worker."""

    __slots__ = ("task_id", "cancelled", "done", "waiter", "delay_deadline",
                 "wait_task_id", "wait_node_id", "waiting", "wait_deadline",
                 "callbacks")

    def __init__(self, task_id: str, delay_deadline: float,
                 wait_task_id: str, wait_node_id: str):
        self.task_id = task_id
        self.cancelled = False
        self.done = threading.Event()
        self.waiter = False
        self.delay_deadline = delay_deadline
        self.wait_task_id = wait_task_id   # "" = no wait
        self.wait_node_id = wait_node_id
        self.waiting = False               # True once in the wait-stop phase
        self.wait_deadline = 0.0
        self.callbacks: List[Callable[[], None]] = []


class Supervisor:
    def __init__(self, store: MemoryStore, start_worker: bool = True):
        """``start_worker=False`` runs no timer thread: the caller (the
        deterministic simulator) pumps ``drive()`` under its own clock —
        identical deadline/wait-stop semantics, zero threads."""
        self.store = store
        self._mu = threading.Lock()
        self._delays: Dict[str, _DelayedStart] = {}
        self._history: Dict[str, Dict[common.SlotTuple, _RestartInfo]] = {}
        self.task_timeout = DEFAULT_OLD_TASK_TIMEOUT
        self._start_worker = start_worker
        self._worker: Optional[threading.Thread] = None
        self._stopped = False
        self._heap: List = []   # (deadline, seq, _DelayedStart)
        self._seq = 0
        self._sub = None
        self._orphans: List[_DelayedStart] = []  # replaced, to be completed

    # ------------------------------------------------------------ restarting

    def restart(self, tx: WriteTx, cluster: Optional[Cluster],
                service: Service, t: Task) -> None:
        """Shut down t and create a replacement if policy allows.

        Must be called inside a store.update transaction (reference:
        restart.go:117 Restart).
        """
        with self._mu:
            old_delay = self._delays.get(t.id)
            if old_delay is not None:
                # t is itself a delayed-start replacement that has not
                # started yet; restart it after the delay completes
                # (reference: restart.go:124-139)
                if not old_delay.waiter:
                    old_delay.waiter = True
                    old_delay.callbacks.append(
                        lambda: self._restart_after_delay(cluster, t.id))
                return

        if t.desired_state > TaskState.COMPLETE:
            raise RuntimeError(
                "restart called on task that was already shut down")

        t = t.copy()
        t.desired_state = TaskState.SHUTDOWN
        tx.update(t)

        if not self._should_restart(t, service):
            return

        if common.is_replicated_service(service) \
                or common.is_replicated_job(service):
            restart_task = common.new_task(cluster, service, t.slot, "")
        elif common.is_global_service(service) \
                or common.is_global_job(service):
            restart_task = common.new_task(cluster, service, 0, t.node_id)
        else:
            log.error("service not supported by restart supervisor")
            return

        if common.is_replicated_job(service) or common.is_global_job(service):
            from ..models.types import Version
            restart_task.job_iteration = Version(
                service.job_status.job_iteration.index
                if service.job_status else 0)

        n = tx.get(Node, t.node_id) if t.node_id else None

        restart_task.desired_state = TaskState.READY

        restart_delay = 0.0
        # restart delay is not applied on drained nodes
        if n is None or n.spec.availability != NodeAvailability.DRAIN:
            if t.spec.restart is not None:
                restart_delay = t.spec.restart.delay
            else:
                restart_delay = common.DEFAULT_RESTART_DELAY

        # normally wait for the old task to stop running; skip if it's
        # already dead or its node is down
        wait_stop = not ((n is not None
                          and n.status.state == NodeState.DOWN)
                         or t.status.state > TaskState.RUNNING)

        tx.create(restart_task)

        tuple_ = common.SlotTuple(
            service_id=restart_task.service_id, slot=restart_task.slot,
            node_id=restart_task.node_id if not restart_task.slot else "")
        self.record_restart_history(tuple_, restart_task)
        self.delay_start(t, restart_task.id, restart_delay, wait_stop)

    def _restart_after_delay(self, cluster: Optional[Cluster],
                             task_id: str) -> None:
        def cb(tx: WriteTx) -> None:
            t = tx.get(Task, task_id)
            if t is None or t.desired_state > TaskState.RUNNING:
                return
            service = tx.get(Service, t.service_id)
            if service is None:
                return
            self.restart(tx, cluster, service, t)

        try:
            self.store.update(cb)
        except Exception:
            log.exception("failed to restart task after waiting for "
                          "previous restart")

    # -------------------------------------------------------------- policy

    def _should_restart(self, t: Task, service: Service) -> bool:
        condition = common.restart_condition(t)
        if condition == RestartCondition.ANY:
            if (common.is_replicated_job(service)
                    or common.is_global_job(service)) \
                    and t.status.state == TaskState.COMPLETE:
                return False
        elif condition == RestartCondition.ON_FAILURE:
            if t.status.state == TaskState.COMPLETE:
                return False
        else:  # NONE
            return False

        if t.spec.restart is None or t.spec.restart.max_attempts == 0:
            return True

        tuple_ = common.SlotTuple(service_id=t.service_id, slot=t.slot)
        if common.is_global_service(service):
            tuple_ = common.SlotTuple(service_id=t.service_id,
                                      node_id=t.node_id)

        with self._mu:
            info = self._history.get(t.service_id, {}).get(tuple_)
            if info is None or (t.spec_version is not None
                                and t.spec_version.index != info.spec_version):
                return True

            max_attempts = t.spec.restart.max_attempts
            window = t.spec.restart.window
            if not window:
                return info.total_restarts < max_attempts

            if not info.restarted_instances:
                return True

            timestamp = t.status.applied_at or t.status.timestamp or now()
            lookback = timestamp - window

            # drop restarts before the lookback window
            instances = [s for s in info.restarted_instances if s > lookback]
            info.restarted_instances = instances
            # ignore restarts that happened after this task's timestamp
            num = sum(1 for s in instances if s < timestamp)
            return num < max_attempts

    def updatable_tasks_in_slot(self, slot: common.Slot,
                                service: Service) -> common.Slot:
        """reference: restart.go:333 UpdatableTasksInSlot."""
        if not slot:
            return []
        updatable = [t for t in slot if t.desired_state <= TaskState.RUNNING]
        if updatable:
            return updatable
        from ..models.types import UpdateState
        if service.update_status is not None and \
                service.update_status.state == UpdateState.ROLLBACK_STARTED:
            return []
        newest = max(slot, key=common.task_timestamp)
        if not self._should_restart(newest, service):
            return [newest]
        return []

    def record_restart_history(self, tuple_: common.SlotTuple,
                               replacement: Task) -> None:
        if replacement.spec.restart is None \
                or replacement.spec.restart.max_attempts == 0:
            return
        with self._mu:
            per_service = self._history.setdefault(
                replacement.service_id, {})
            info = per_service.setdefault(tuple_, _RestartInfo())
            if replacement.spec_version is not None and \
                    replacement.spec_version.index != info.spec_version:
                info.total_restarts = 0
                info.restarted_instances = []
                info.spec_version = replacement.spec_version.index
            info.total_restarts += 1
            if replacement.spec.restart.window:
                info.restarted_instances.append(
                    replacement.meta.created_at or now())

    # -------------------------------------------------------- delayed starts

    def delay_start(self, old_task: Optional[Task], new_task_id: str,
                    delay: float, wait_stop: bool) -> threading.Event:
        """Move new_task READY->RUNNING after the delay elapses and the old
        task stops (or times out).  Returns the completion event
        (reference: restart.go:427 DelayStart)."""
        # a task that was never assigned has no agent to report its stop
        # — waiting on it would just burn task_timeout (rolling updates
        # replacing a still-PENDING restart replacement hit this)
        wait_for_task = (wait_stop and old_task is not None
                         and old_task.status.state <= TaskState.RUNNING
                         and (bool(old_task.node_id)
                              or old_task.status.state
                              >= TaskState.ASSIGNED))
        ds = _DelayedStart(
            new_task_id, now() + delay,
            old_task.id if wait_for_task else "",
            old_task.node_id if wait_for_task else "")
        with self._mu:
            old = self._delays.pop(new_task_id, None)
            if old is not None:
                # keep it visible to the sweep so its done event fires and
                # any waiter callbacks run promptly
                old.cancelled = True
                self._orphans.append(old)
            self._delays[new_task_id] = ds
            self._seq += 1
            heapq.heappush(self._heap, (ds.delay_deadline, self._seq, ds))
            self._ensure_worker_locked()
        if self._sub is not None:
            self._sub.wake()   # react to the new deadline without poll lag
        return ds.done

    def _ensure_worker_locked(self) -> None:
        if self._sub is None:
            # accepts_blocks: pred drops them — assignment blocks are
            # state<=RUNNING by store contract, never failures
            self._sub = self.store.queue.subscribe(
                self._event_pred, accepts_blocks=True)
        if not self._start_worker:
            return   # simulator mode: drive() pumps instead of a thread
        if self._worker is None or not self._worker.is_alive():
            self._stopped = False
            self._worker = threading.Thread(
                target=self._worker_loop, name="restart-timer", daemon=True)
            self._worker.start()

    def drive(self) -> None:
        """One synchronous pump of the timer machinery (start_worker=False
        mode): handle buffered stop events, sweep cancellations, fire due
        deadlines.  Exactly one _worker_loop iteration, minus the thread
        and the blocking get — the simulator calls this every control
        step under virtual time."""
        from ..state.watch import Subscription
        if self._sub is None:
            if self._stopped:
                return   # deposed: never resubscribe a dead supervisor
            with self._mu:
                self._ensure_worker_locked()
        while True:
            # re-read per iteration: a stop event's completion write
            # pumps consensus (virtual time), and a deposal inside that
            # pump runs stop() re-entrantly, nulling the subscription
            sub = self._sub
            if sub is None:
                return
            ev = sub.poll()
            if ev is None:
                break
            if ev is not Subscription.WAKE:
                self._handle_stop_event(ev)
        self._sweep_cancelled()
        self._fire_due()

    @staticmethod
    def _event_pred(ev) -> bool:
        if not isinstance(ev, Event):
            return False
        obj = ev.obj
        if isinstance(obj, Task):
            # a deleted task (reaper cleanup) can never report a stop —
            # release its waiters instead of sitting out task_timeout
            return (ev.action == "delete"
                    or (ev.action == "update"
                        and obj.status.state > TaskState.RUNNING))
        if isinstance(obj, Node):
            return (ev.action == "delete"
                    or (ev.action == "update"
                        and obj.status.state == NodeState.DOWN))
        return False

    def _worker_loop(self) -> None:
        from ..state.watch import Closed, Subscription
        while not self._stopped:
            with self._mu:
                deadline = self._heap[0][0] if self._heap else None
            timeout = 0.2 if deadline is None else \
                min(0.2, max(0.0, deadline - now()))
            ev = None
            try:
                ev = self._sub.get(timeout=timeout) if timeout > 0 else None
            except TimeoutError:
                pass
            except Closed:
                break
            if ev is not None and ev is not Subscription.WAKE:
                self._handle_stop_event(ev)
            self._sweep_cancelled()
            self._fire_due()
        # final pass: complete whatever remains so done events always fire
        self._sweep_cancelled()

    def _handle_stop_event(self, ev: Event) -> None:
        """An old task stopped or its node died: release waiting entries."""
        obj = ev.obj
        ready: List[_DelayedStart] = []
        with self._mu:
            for ds in self._delays.values():
                if not ds.waiting or ds.cancelled:
                    continue
                if (isinstance(obj, Task) and obj.id == ds.wait_task_id) or \
                        (isinstance(obj, Node) and obj.id == ds.wait_node_id):
                    ready.append(ds)
        for ds in ready:
            self._complete(ds)

    def _sweep_cancelled(self) -> None:
        with self._mu:
            cancelled = [ds for ds in self._delays.values()
                         if ds.cancelled and not ds.done.is_set()]
            cancelled.extend(self._orphans)
            self._orphans = []
        for ds in cancelled:
            self._complete(ds)

    def _fire_due(self) -> None:
        ts = now()
        while True:
            with self._mu:
                if not self._heap or self._heap[0][0] > ts:
                    return
                _, _, ds = heapq.heappop(self._heap)
                if ds.done.is_set():
                    continue
                if not ds.cancelled and not ds.waiting and ds.wait_task_id:
                    # delay elapsed; wait only if the old task may still
                    # stop gracefully: it reads <= RUNNING *and* its node is
                    # alive (a node that died during the delay phase will
                    # never report the stop — don't sit out task_timeout)
                    cur = self.store.raw_get(Task, ds.wait_task_id)
                    node = self.store.raw_get(Node, ds.wait_node_id) \
                        if ds.wait_node_id else None
                    node_dead = (ds.wait_node_id
                                 and (node is None or node.status.state
                                      == NodeState.DOWN))
                    if cur is not None and not node_dead and \
                            cur.status.state <= TaskState.RUNNING:
                        ds.waiting = True
                        ds.wait_deadline = ts + self.task_timeout
                        self._seq += 1
                        heapq.heappush(self._heap,
                                       (ds.wait_deadline, self._seq, ds))
                        continue
            self._complete(ds)

    def _complete(self, ds: _DelayedStart) -> None:
        """Fire the READY->RUNNING transition and mark done.  Runs outside
        _mu: start_now and the callbacks take store locks, and restart()
        (which can run inside a store transaction) takes _mu — completing
        under _mu would invert that order."""
        with self._mu:
            if ds.done.is_set():
                return
            cancelled = ds.cancelled
        if not cancelled:
            try:
                self.start_now(ds.task_id)
            except Exception:
                log.exception("moving task to RUNNING failed")
        with self._mu:
            if ds.done.is_set():
                return
            if self._delays.get(ds.task_id) is ds:
                del self._delays[ds.task_id]
            callbacks, ds.callbacks = ds.callbacks, []
            ds.done.set()
        for cb in callbacks:
            try:
                cb()
            except Exception:
                log.exception("delayed-start callback failed")

    def start_now_tx(self, tx: WriteTx, task_id: str) -> None:
        """Move the task out of its delayed state inside an open
        transaction: job tasks (those carrying a job_iteration) run to
        desired COMPLETE, service tasks to desired RUNNING (reference:
        restart.go StartNow's JobIteration branch)."""
        t = tx.get(Task, task_id)
        if t is None or t.desired_state >= TaskState.RUNNING:
            return
        t = t.copy()
        t.desired_state = (TaskState.COMPLETE if t.job_iteration is not None
                           else TaskState.RUNNING)
        tx.update(t)

    def start_now(self, task_id: str) -> None:
        """Moves the task to the RUNNING state (reference: StartNow)."""
        self.store.update(lambda tx: self.start_now_tx(tx, task_id))

    def cancel(self, task_id: str) -> None:
        with self._mu:
            ds = self._delays.get(task_id)
            if ds is not None:
                ds.cancelled = True
        if ds is not None:
            if self._sub is not None:
                self._sub.wake()
            ds.done.wait(timeout=5)

    def cancel_all(self) -> None:
        with self._mu:
            for ds in self._delays.values():
                ds.cancelled = True
        if self._sub is not None:
            self._sub.wake()

    def stop(self) -> None:
        """Shut the timer worker down (manager demotion/shutdown)."""
        self.cancel_all()
        self._stopped = True
        if self._sub is not None:
            # closing the subscription pops the worker out of get(); its
            # exit path runs a final sweep so pending done events fire
            self.store.queue.unsubscribe(self._sub)
        if self._worker is not None:
            self._worker.join(timeout=5)
            self._worker = None
        self._sub = None
        # belt-and-braces: if the worker was wedged, complete leftovers here
        with self._mu:
            leftovers = ([ds for ds in self._delays.values()
                          if not ds.done.is_set()] + self._orphans)
            self._orphans = []
        for ds in leftovers:
            self._complete(ds)

    def clear_service_history(self, service_id: str) -> None:
        with self._mu:
            self._history.pop(service_id, None)
