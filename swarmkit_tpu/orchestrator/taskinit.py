"""Startup task-consistency pass: fix orphans and resume interrupted
delayed starts left behind by the previous leader.

Reference: manager/orchestrator/taskinit/init.go.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from ..models.objects import Service, Task
from ..models.types import TaskState, now
from ..state.store import Batch, MemoryStore, ReadTx
from . import common
from .restart import Supervisor as RestartSupervisor

log = logging.getLogger("taskinit")


def check_tasks(store: MemoryStore, read_tx: ReadTx, init_handler,
                restarts: RestartSupervisor) -> None:
    """Fix tasks in the store before an orchestrator runs.

    ``init_handler`` provides: is_related_service(service) -> bool,
    fix_task(batch, task), slot_tuple(task) -> SlotTuple
    (reference: init.go:19 InitHandler).
    """
    instances: Dict[common.SlotTuple, List[Task]] = {}

    def cb(batch: Batch) -> None:
        for t in read_tx.find(Task):
            if not t.service_id:
                continue
            service = read_tx.get(Service, t.service_id)
            if service is None:
                # service was deleted; clean up the task
                def delete(tx, tid=t.id):
                    try:
                        tx.delete(Task, tid)
                    except Exception:
                        pass
                batch.update(delete)
                continue
            if not init_handler.is_related_service(service):
                continue

            tuple_ = init_handler.slot_tuple(t)
            instances.setdefault(tuple_, []).append(t)

            init_handler.fix_task(batch, t)

            # desired state READY is transient: the previous leader may not
            # have started it — retry the delayed start here
            if (t.desired_state != TaskState.READY
                    or t.status.state > TaskState.COMPLETE):
                continue
            restart_delay = common.DEFAULT_RESTART_DELAY
            if t.spec.restart is not None:
                restart_delay = t.spec.restart.delay
            if restart_delay:
                timestamp = t.status.applied_at or t.status.timestamp
                if timestamp:
                    remaining = (timestamp + restart_delay) - now()
                    restart_delay = min(remaining, restart_delay)
                if restart_delay > 0:
                    restarts.delay_start(None, t.id, restart_delay, True)
                    continue

            def start(tx, tid=t.id):
                restarts.start_now_tx(tx, tid)
            batch.update(start)

    store.batch(cb)

    # reconstruct restart history from retained task rows
    for tuple_, instance in instances.items():
        max_version = max((t.spec_version.index for t in instance
                           if t.spec_version is not None), default=0)
        up_to_date = [t for t in instance
                      if t.spec_version is not None
                      and t.spec_version.index == max_version]
        up_to_date.sort(key=lambda t: t.meta.created_at or 0.0)
        for t in up_to_date[1:]:
            restarts.record_restart_history(tuple_, t)
