"""Task reaper: deletes historic tasks beyond TaskHistoryRetentionLimit and
tasks marked desired-REMOVE once shut down.

Reference: manager/orchestrator/taskreaper/task_reaper.go.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set

from ..models.objects import Cluster, Service, Task
from ..models.specs import ServiceMode
from ..models.types import TaskState
from ..state.events import Event
from ..state.store import (
    Batch, ByDesiredState, ByName, ByNode, BySlot, ByTaskState, MemoryStore,
)
from ..state.watch import Closed
from . import common
from .replicated import DEFAULT_CLUSTER_NAME

log = logging.getLogger("taskreaper")

MAX_DIRTY = 1000                  # reference: task_reaper.go:17
REAPER_BATCHING_INTERVAL = 0.250  # reference: task_reaper.go:19


def _task_in_terminal_state(t: Task) -> bool:
    return t.status.state > TaskState.RUNNING


def _task_will_never_run(t: Task) -> bool:
    return (t.status.state < TaskState.ASSIGNED
            and t.desired_state > TaskState.RUNNING)


class TaskReaper:
    def __init__(self, store: MemoryStore):
        self.store = store
        self.task_history = 5
        self.dirty: Set[common.SlotTuple] = set()
        self.cleanup: List[str] = []
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="taskreaper",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)

    def run(self) -> None:
        try:
            def init(tx):
                for c in tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)):
                    self.task_history = \
                        c.spec.orchestration.task_history_retention_limit
                orphaned = tx.find(Task, ByTaskState(TaskState.ORPHANED))
                removed = tx.find(Task, ByDesiredState(TaskState.REMOVE))
                for t in orphaned:
                    # serviceless orphans can be cleaned right away; service
                    # tasks go through regular history cleanup
                    if not t.service_id:
                        self.cleanup.append(t.id)
                for t in removed:
                    if (t.status.state < TaskState.ASSIGNED
                            or t.status.state >= TaskState.COMPLETE):
                        self.cleanup.append(t.id)

            # accepts_blocks: reaping triggers on creates, orphaned and
            # REMOVE-desired terminal states — assignment blocks
            # (state<=RUNNING by store contract) match none of those
            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            try:
                if self.cleanup:
                    self.tick()
                deadline: Optional[float] = None
                from ..models.types import now
                while not self._stop.is_set():
                    timeout = 0.2 if deadline is None else \
                        max(0.0, min(0.2, deadline - now()))
                    event = None
                    try:
                        event = sub.get(timeout=timeout) if timeout > 0 \
                            else None
                    except TimeoutError:
                        pass
                    except Closed:
                        return
                    if event is not None and isinstance(event, Event):
                        self._handle_event(event)
                        if len(self.dirty) + len(self.cleanup) > MAX_DIRTY:
                            deadline = None
                            self.tick()
                        elif deadline is None:
                            deadline = now() + REAPER_BATCHING_INTERVAL
                    elif deadline is not None and now() >= deadline:
                        deadline = None
                        self.tick()
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _handle_event(self, ev: Event) -> None:
        obj = ev.obj
        if isinstance(obj, Task):
            if ev.action == "create":
                self.dirty.add(common.SlotTuple(
                    service_id=obj.service_id, slot=obj.slot,
                    node_id=obj.node_id))
            elif ev.action == "update":
                t = obj
                if t.status.state >= TaskState.ORPHANED and not t.service_id:
                    self.cleanup.append(t.id)
                if t.desired_state == TaskState.REMOVE and (
                        t.status.state < TaskState.ASSIGNED
                        or t.status.state >= TaskState.COMPLETE):
                    self.cleanup.append(t.id)
        elif isinstance(obj, Cluster) and ev.action == "update":
            self.task_history = \
                obj.spec.orchestration.task_history_retention_limit

    def tick(self) -> None:
        """reference: task_reaper.go:231 tick."""
        if not self.dirty and not self.cleanup:
            return
        delete_tasks: Set[str] = set(self.cleanup)
        self.cleanup = []

        def read(tx):
            for dirty in list(self.dirty):
                service = tx.get(Service, dirty.service_id)
                if service is None:
                    self.dirty.discard(dirty)
                    continue
                task_history = self.task_history
                # MaxAttempts forces retention for restart-history rebuild
                restart = service.spec.task.restart
                if restart is not None and restart.max_attempts > 0:
                    task_history = restart.max_attempts + 1
                if task_history < 0:
                    self.dirty.discard(dirty)
                    continue

                if service.spec.mode == ServiceMode.REPLICATED:
                    historic = tx.find(
                        Task, BySlot(dirty.service_id, dirty.slot))
                elif service.spec.mode == ServiceMode.GLOBAL:
                    historic = [t for t in tx.find(Task, ByNode(dirty.node_id))
                                if t.service_id == dirty.service_id]
                else:
                    # jobs keep their history until service deletion
                    self.dirty.discard(dirty)
                    continue

                if len(historic) <= task_history:
                    self.dirty.discard(dirty)
                    continue

                historic.sort(key=common.task_timestamp)

                running = 0
                for t in historic:
                    if _task_in_terminal_state(t) or _task_will_never_run(t):
                        delete_tasks.add(t.id)
                        task_history += 1
                        if len(historic) <= task_history:
                            break
                    else:
                        running += 1
                # keep the slot dirty only while >1 running tasks remain
                if running <= 1:
                    self.dirty.discard(dirty)

        self.store.view(read)

        if delete_tasks:
            def cb(batch: Batch) -> None:
                for task_id in delete_tasks:
                    def one(tx, task_id=task_id):
                        try:
                            tx.delete(Task, task_id)
                        except Exception:
                            pass
                    batch.update(one)
            try:
                self.store.batch(cb)
            except Exception:
                log.exception("task reaper cleanup batch failed")
