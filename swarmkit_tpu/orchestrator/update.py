"""Rolling-update supervisor: one Updater per service, a
parallelism-bounded window of in-flight slot replacements, start-first/
stop-first ordering, failure monitoring with pause/rollback.

Reference: manager/orchestrator/update/updater.go.

Design difference from the reference (and from this module's first
shape): the updater is an explicit state machine pumped by ``drive()``
instead of one goroutine per slot.  Production runs it on a single
thread per updater (``Supervisor(start_worker=True)``: the thread loops
drive + event wait); the deterministic simulator constructs the
supervisor with ``start_worker=False`` and pumps ``drive()`` from its
control step under virtual time — same FSM, zero threads, mirroring
orchestrator/restart.py.  All deadlines (batch delay, monitor window)
read time through the ``models.types.now()`` seam, and every store
write rides ``store.update`` — which pins the proposal to the
leadership epoch read at commit start, so a deposed leader's rollout
writes are fenced, not silently committed.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Dict, List, Optional

from ..models.objects import Cluster, Service, Task
from ..models.types import (
    TaskState, UpdateFailureAction, UpdateOrder, UpdateState, UpdateStatus,
    now,
)
from ..state.events import Event
from ..state.store import MemoryStore, WriteTx
from ..utils.metrics import registry as _metrics
from . import common
from .restart import Supervisor as RestartSupervisor

log = logging.getLogger("update")


def _specs_equal(a, b) -> bool:
    return a is b or dataclasses.asdict(a) == dataclasses.asdict(b)


def _state_gauge(service_id: str, state: UpdateState) -> None:
    _metrics.gauge(f'swarm_update_state{{service="{service_id}"}}',
                   float(int(state)))


def _clear_state_gauge(service_id: str) -> None:
    """Service gone mid-rollout: park the state gauge at -1 (no update)
    so the ``stuck_rollout`` health check stops judging a frozen
    UPDATING stamp for a service that no longer exists."""
    _metrics.gauge(f'swarm_update_state{{service="{service_id}"}}', -1.0)


def _progress_gauge(service_id: str) -> None:
    """Stamp of the update's last forward progress; the ``stuck_rollout``
    health check fails when an UPDATING service stops moving for longer
    than its monitor window (obs/health.py)."""
    _metrics.gauge(
        f'swarm_update_last_progress{{service="{service_id}"}}', now())


def _edge_timer(edge: str, dt: float) -> None:
    _metrics.timer(f'swarm_update_rollout{{edge="{edge}"}}').observe(dt)


class Supervisor:
    """Tracks at most one in-flight Updater per service
    (reference: updater.go:26)."""

    def __init__(self, store: MemoryStore, restarts: RestartSupervisor,
                 start_worker: bool = True):
        """``start_worker=False`` spawns no threads: the caller (the
        deterministic simulator's control step) pumps ``drive()`` under
        its own clock — identical FSM semantics, zero threads."""
        self.store = store
        self.restarts = restarts
        self._start_worker = start_worker
        self._mu = threading.Lock()
        self._updates: Dict[str, "Updater"] = {}

    def update(self, cluster: Optional[Cluster], service: Service,
               slots: List[common.Slot]) -> None:
        with self._mu:
            existing = self._updates.get(service.id)
            if existing is not None and not existing.finished:
                if _specs_equal(service.spec, existing.new_service.spec):
                    return  # already working towards this goal
                # blocking cancel serializes updaters per service: the old
                # one must be fully out of its slots before the new one
                # touches them (reference: updater.go:56-61).  Threadless
                # mode aborts synchronously (same thread); threaded mode
                # waits for the drive loop to exit — safe under _mu, the
                # loop sets its done event before the cleanup closure
                # re-takes _mu.
                existing.cancel()
            updater = Updater(self.store, self.restarts, cluster, service,
                              threadless=not self._start_worker)
            self._updates[service.id] = updater

        if not self._start_worker:
            updater.begin(slots)
            return

        def run():
            updater.run(slots)
            with self._mu:
                if self._updates.get(service.id) is updater:
                    del self._updates[service.id]

        threading.Thread(target=run, name=f"updater-{service.id[:8]}",
                         daemon=True).start()

    def drive(self) -> None:
        """One synchronous pump of every in-flight updater
        (start_worker=False mode); finished updaters are reaped.  A
        store-write failure (leadership loss) propagates to the caller —
        the simulator's control step handles the deposal."""
        with self._mu:
            updaters = list(self._updates.items())
        for service_id, u in updaters:
            if not u.finished:
                u.drive()
            if u.finished:
                with self._mu:
                    if self._updates.get(service_id) is u:
                        del self._updates[service_id]

    def cancel_all(self) -> None:
        with self._mu:
            updates = list(self._updates.values())
        for u in updates:
            u.cancel()


class _SlotState:
    """One in-flight slot replacement.  Phases:

    * ``delay``    — waiting for the restart supervisor's delayed start
                     (old task stopping / restart delay) to complete
    * ``running``  — waiting for the replacement task to reach RUNNING
                     (or any terminal state; failures are accounted by
                     the monitor, not re-waited here)
    * ``cooldown`` — per-batch ``delay`` between slots, occupying a
                     parallelism window seat (reference worker sleep)
    """

    __slots__ = ("slot", "uid", "phase", "delay_done", "deadline",
                 "start_first")

    def __init__(self, slot: common.Slot):
        self.slot = slot
        self.uid = ""          # replacement task id ("" = none created)
        self.phase = "delay"
        self.delay_done = None  # threading.Event from delay_start
        self.deadline = 0.0     # cooldown deadline
        self.start_first = False


class Updater:
    """Updates one service's slots to the new spec
    (reference: updater.go:85)."""

    #: checker-sensitivity seam (tests/test_update_chaos.py): when False,
    #: a failure-threshold PAUSE still writes the paused status but does
    #: NOT halt the rollout — the sim's pause-on-failure-threshold
    #: invariant must catch the update claiming new slots while paused.
    _pause_halts = True

    def __init__(self, store: MemoryStore, restarts: RestartSupervisor,
                 cluster: Optional[Cluster], new_service: Service,
                 threadless: bool = False):
        self.store = store
        self.restarts = restarts
        self.cluster = cluster.copy() if cluster else None
        self.new_service = new_service.copy()
        self.threadless = threadless
        self.finished = False
        self._stop = threading.Event()
        self._done = threading.Event()
        self._mu = threading.Lock()
        self._updated_tasks: Dict[str, float] = {}  # id -> RUNNING stamp
        # ----- FSM state
        self._pending: List[common.Slot] = []
        self._in_flight: List[_SlotState] = []
        self._monitor_deadline: Optional[float] = None
        self._sub = None
        self._failed_tasks: set = set()
        self._total_failures = 0
        self._stopped = False
        self._rollback = False
        self._config = None
        self._monitoring_period = 30.0
        self._parallelism = 1
        self._n_dirty = 0
        self._watch_failures = False

    def cancel(self) -> None:
        """Stop the rollout without completing it.  Never writes the
        store (a deposed leader's teardown must not stage writes)."""
        self._stop.set()
        if self.threadless:
            self._abort()
            return
        if self._sub is not None:
            self._sub.wake()
        # must outlast the drive loop so per-service serialization holds:
        # a successor updater may not start while this one can still
        # touch slots
        self._done.wait(timeout=30)

    # ------------------------------------------------------------ threaded

    def run(self, slots: List[common.Slot]) -> None:
        """Threaded entry point: begin + drive loop on one thread."""
        from ..state.watch import Closed
        try:
            self.begin(slots)
            while not self.finished:
                if self._stop.is_set():
                    self._abort()
                    break
                self.drive()
                if self.finished or self._sub is None:
                    break
                try:
                    ev = self._sub.get(timeout=0.2)
                except TimeoutError:
                    continue
                except Closed:
                    self._abort()
                    break
                self._intake(ev)
        except Exception:
            log.exception("updater failed")
            self._abort()
        finally:
            self._abort()   # no-op when already finished cleanly
            self._done.set()

    # ----------------------------------------------------------------- begin

    def begin(self, slots: List[common.Slot]) -> None:
        """Classify slots and start the FSM.  May finish immediately
        (paused service, nothing dirty)."""
        service = self.new_service
        us = service.update_status
        if us is not None and us.state in (UpdateState.PAUSED,
                                           UpdateState.ROLLBACK_PAUSED):
            self._finish()
            return

        dirty_slots = [s for s in slots if self._is_slot_dirty(s)]
        if not dirty_slots:
            if us is not None and us.state in (UpdateState.UPDATING,
                                               UpdateState.ROLLBACK_STARTED):
                self._complete_update(service.id)
            self._finish()
            return

        if us is None:
            self._start_update(service.id)

        self._rollback = us is not None and \
            us.state == UpdateState.ROLLBACK_STARTED
        self._config = common.update_config_for(service, self._rollback)
        self._monitoring_period = self._config.monitor or 30.0
        if self._config.delay >= self._monitoring_period:
            self._monitoring_period = self._config.delay + 1.0
        self._parallelism = self._config.parallelism or len(dirty_slots)
        self._n_dirty = len(dirty_slots)
        self._watch_failures = (self._config.failure_action
                                != UpdateFailureAction.CONTINUE)
        _metrics.gauge(
            f'swarm_update_monitor{{service="{service.id}"}}',
            self._monitoring_period)
        self._pending = list(dirty_slots)

        sid = service.id

        def pred(ev):
            # every update event for this service's tasks: failures feed
            # the monitor, >=RUNNING flips complete in-flight slots.
            # accepts_blocks below, but blocks (EventTaskBlock) fail the
            # isinstance and are dropped: assignment blocks carry only
            # scheduler-band states, the RUNNING flip and every failure
            # arrive as per-object events (store contract)
            return (isinstance(ev, Event) and ev.action == "update"
                    and isinstance(ev.obj, Task)
                    and ev.obj.service_id == sid)

        self._sub = self.store.queue.subscribe(pred, accepts_blocks=True)
        self.drive()

    # ----------------------------------------------------------------- drive

    def drive(self) -> None:
        """One synchronous pump: intake task events, advance the
        in-flight window, refill it, run the monitor window, complete."""
        if self.finished:
            return
        if self._stop.is_set():
            self._abort()
            return
        # 1. event intake (failures + RUNNING flips)
        if self._sub is not None:
            from ..state.watch import Subscription
            while True:
                ev = self._sub.poll()
                if ev is None:
                    break
                if ev is not Subscription.WAKE:
                    self._intake(ev)
                if self.finished:
                    return
        # 2. advance in-flight slots
        ts = now()
        still = []
        for ss in self._in_flight:
            self._advance_slot(ss, ts)
            if ss.phase != "done":
                still.append(ss)
            else:
                _progress_gauge(self.new_service.id)
        self._in_flight = still
        if self.finished or self._stopped:
            if self._stopped:
                self._finish()
            return
        # 3. refill the window
        while self._pending and len(self._in_flight) < self._parallelism:
            slot = self._pending.pop(0)
            try:
                ss = self._begin_slot(slot)
            except Exception:
                if self.threadless:
                    raise   # sim: leadership loss handled by the caller
                log.exception("update failed")
                continue
            if self.finished or self._stopped:
                if self._stopped:
                    self._finish()
                return
            if ss is not None:
                self._advance_slot(ss, now())
                if ss.phase != "done":
                    self._in_flight.append(ss)
        # 4. monitor window, then completion
        if self._pending or self._in_flight:
            return
        if self._monitor_deadline is None:
            if not self._watch_failures:
                # CONTINUE never monitors (reference parity)
                self._complete_update(self.new_service.id)
                self._finish()
                return
            self._monitor_deadline = now() + self._monitoring_period
            return
        if now() >= self._monitor_deadline:
            self._complete_update(self.new_service.id)
            self._finish()

    def _finish(self) -> None:
        if self.finished:
            return
        self.finished = True
        if self._sub is not None:
            try:
                self.store.queue.unsubscribe(self._sub)
            except Exception:
                pass
            self._sub = None
        self._done.set()

    def _abort(self) -> None:
        """Teardown without completion and WITHOUT store writes."""
        self._stopped = True
        self._finish()

    # ---------------------------------------------------------- event intake

    def _intake(self, ev) -> None:
        if not isinstance(ev, Event) or not isinstance(ev.obj, Task):
            return
        t = ev.obj
        state = TaskState(t.status.state)
        if self._watch_failures and state > TaskState.RUNNING:
            self._on_failure(t)

    def _slot_running(self, ss: _SlotState, t: Task,
                      state: TaskState) -> None:
        """The replacement reached RUNNING (or died trying — the monitor
        accounts failures; this slot's wait is over either way).  In
        start-first order the old task comes down only on a LIVE
        replacement (state == RUNNING exactly): a replacement observed
        already-dead keeps the old task serving — even when RUNNING
        flashed by between two pumps — and the next reconcile re-dirties
        the slot once the restart supervisor produces a survivor."""
        with self._mu:
            self._updated_tasks[ss.uid] = now()
        if ss.start_first and state == TaskState.RUNNING:
            def rm(tx: WriteTx) -> None:
                self._remove_old_tasks(tx, ss.slot)
            try:
                self.store.update(rm)
            except Exception:
                if self.threadless:
                    raise
                log.exception("failed to remove old task after starting "
                              "replacement")
        self._enter_cooldown(ss)

    def _on_failure(self, failed_task: Task) -> bool:
        """reference: updater.go:222 — one failure may trip the
        configured failure action once the ratio threshold is crossed."""
        if failed_task.id in self._failed_tasks:
            return False
        with self._mu:
            started_at = self._updated_tasks.get(failed_task.id)
        if started_at is None:
            return False
        if started_at and now() - started_at > self._monitoring_period:
            return False
        self._failed_tasks.add(failed_task.id)
        self._total_failures += 1
        if (self._total_failures / self._n_dirty
                <= self._config.max_failure_ratio):
            return False
        action = self._config.failure_action
        if action == UpdateFailureAction.PAUSE or \
                (action == UpdateFailureAction.ROLLBACK and self._rollback):
            # never roll back a rollback: it pauses instead
            kind = "rollback" if self._rollback else "update"
            self._pause_update(
                self.new_service.id,
                f"{kind} paused due to failure or early termination "
                f"of task {failed_task.id}")
            if self._pause_halts:
                self._stopped = True
                self._finish()
            return True
        if action == UpdateFailureAction.ROLLBACK:
            self._rollback_update(
                self.new_service.id,
                "update rolled back due to failure or early "
                f"termination of task {failed_task.id}")
            self._stopped = True
            self._finish()
            return True
        return False

    # -------------------------------------------------------------- slot FSM

    def _advance_slot(self, ss: _SlotState, ts: float) -> None:
        if ss.phase == "delay":
            if ss.delay_done is None or ss.delay_done.is_set():
                if ss.uid:
                    ss.phase = "running"
                else:
                    self._enter_cooldown(ss)   # reused task: no wait
        if ss.phase == "running":
            # poll the row rather than the event stream: the RUNNING flip
            # may have committed while this slot was still in its delay
            # phase, and a consumed event cannot be re-observed (events
            # still wake the threaded loop and feed the failure monitor)
            t = self.store.raw_get(Task, ss.uid)
            if t is None:
                self._enter_cooldown(ss)   # replacement vanished
            else:
                state = TaskState(t.status.state)
                if state >= TaskState.RUNNING:
                    self._slot_running(ss, t, state)
        if ss.phase == "cooldown" and ts >= ss.deadline:
            ss.phase = "done"

    def _enter_cooldown(self, ss: _SlotState) -> None:
        if self._config is not None and self._config.delay:
            ss.phase = "cooldown"
            ss.deadline = now() + self._config.delay
        else:
            ss.phase = "done"

    def _begin_slot(self, slot: common.Slot) -> Optional[_SlotState]:
        """Start updating one slot; returns its in-flight state, or
        None when the slot needed no work and no cooldown applies."""
        running_task = None
        clean_task = None
        for t in slot:
            if not self._is_task_dirty(t):
                if t.desired_state == TaskState.RUNNING:
                    running_task = t
                    break
                if t.desired_state < TaskState.RUNNING:
                    clean_task = t
        if running_task is not None:
            return self._use_existing_task(slot, running_task)
        if clean_task is not None:
            return self._use_existing_task(slot, clean_task)

        ss = _SlotState(slot)
        node_id = ""
        if common.is_global_service(self.new_service):
            node_id = slot[0].node_id
        updated = common.new_task(
            self.cluster, self.new_service, slot[0].slot, node_id)
        updated.desired_state = TaskState.READY
        ss.uid = updated.id
        ss.start_first = (self._config.order == UpdateOrder.START_FIRST)
        with self._mu:
            self._updated_tasks[ss.uid] = 0.0

        def txn(tx: WriteTx) -> None:
            """Atomically create the updated task and bring down the old
            one (reference: updater.go:367)."""
            if tx.get(Service, updated.service_id) is None:
                raise RuntimeError("service was deleted")
            tx.create(updated)
            if ss.start_first:
                ss.delay_done = self.restarts.delay_start(
                    None, ss.uid, 0.0, False)
            else:
                old_task = self._remove_old_tasks(tx, slot)
                ss.delay_done = self.restarts.delay_start(
                    old_task, ss.uid, 0.0, True)

        self.store.update(txn)
        return ss

    def _use_existing_task(self, slot: common.Slot,
                           existing: Task) -> Optional[_SlotState]:
        remove = [t for t in slot if t is not existing]
        if not remove and existing.desired_state == TaskState.RUNNING:
            # nothing to change; the cooldown still paces the window
            if self._config is not None and self._config.delay:
                ss = _SlotState(slot)
                self._enter_cooldown(ss)
                return ss
            return None
        ss = _SlotState(slot)

        def txn(tx: WriteTx) -> None:
            old_task = self._remove_old_tasks(tx, remove) if remove else None
            if existing.desired_state != TaskState.RUNNING:
                ss.delay_done = self.restarts.delay_start(
                    old_task, existing.id, 0.0, True)

        self.store.update(txn)
        return ss

    def _remove_old_tasks(self, tx: WriteTx,
                          remove: common.Slot) -> Optional[Task]:
        """Shut down the given tasks; returns one that was shut down
        (reference: updater.go:493)."""
        removed = None
        for original in remove:
            if original.desired_state > TaskState.RUNNING:
                continue
            t = tx.get(Task, original.id)
            if t is None:
                continue
            if t.desired_state > TaskState.RUNNING:
                continue
            t = t.copy()
            t.desired_state = TaskState.SHUTDOWN
            tx.update(t)
            removed = t
        return removed

    # ------------------------------------------------------------ dirtiness

    def _is_task_dirty(self, t: Task) -> bool:
        from ..models.objects import Node
        n = self.store.raw_get(Node, t.node_id) if t.node_id else None
        return common.is_task_dirty(self.new_service, t, n)

    def _is_slot_dirty(self, slot: common.Slot) -> bool:
        return len(slot) > 1 or (len(slot) == 1
                                 and self._is_task_dirty(slot[0]))

    # -------------------------------------------------------- status writes

    def _start_update(self, service_id: str) -> None:
        state = {}

        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None:
                state["deleted"] = True
                return
            if service.update_status is not None:
                return
            service = service.copy()
            service.update_status = UpdateStatus(
                state=UpdateState.UPDATING, started_at=now(),
                message="update in progress")
            state["new"] = UpdateState.UPDATING
            tx.update(service)

        self._status_update(cb, "mark update in progress", service_id,
                            state)

    def _pause_update(self, service_id: str, message: str) -> None:
        state = {}

        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None:
                state["deleted"] = True
                return
            if service.update_status is None:
                return
            service = service.copy()
            state["started"] = service.update_status.started_at
            if service.update_status.state == UpdateState.ROLLBACK_STARTED:
                service.update_status.state = UpdateState.ROLLBACK_PAUSED
                state["edge"] = "rollback_to_paused"
            else:
                service.update_status.state = UpdateState.PAUSED
                state["edge"] = "updating_to_paused"
            service.update_status.message = message
            state["new"] = service.update_status.state
            tx.update(service)

        self._status_update(cb, "pause update", service_id, state)

    def _rollback_update(self, service_id: str, message: str) -> None:
        state = {}

        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None:
                state["deleted"] = True
                return
            if service.update_status is None:
                return
            service = service.copy()
            state["started"] = service.update_status.started_at
            state["edge"] = "updating_to_rollback"
            state["new"] = UpdateState.ROLLBACK_STARTED
            service.update_status.state = UpdateState.ROLLBACK_STARTED
            service.update_status.message = message
            if service.previous_spec is None:
                raise RuntimeError("cannot roll back service because no "
                                   "previous spec is available")
            service.spec = service.previous_spec
            service.spec_version = (service.previous_spec_version.copy()
                                    if service.previous_spec_version else None)
            service.previous_spec = None
            service.previous_spec_version = None
            tx.update(service)

        self._status_update(cb, "start rollback", service_id, state)

    def _complete_update(self, service_id: str) -> None:
        state = {}

        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None:
                state["deleted"] = True
                return
            if service.update_status is None:
                return
            service = service.copy()
            state["started"] = service.update_status.started_at
            if service.update_status.state == UpdateState.ROLLBACK_STARTED:
                service.update_status.state = UpdateState.ROLLBACK_COMPLETED
                service.update_status.message = "rollback completed"
                state["edge"] = "rollback_to_completed"
            else:
                service.update_status.state = UpdateState.COMPLETED
                service.update_status.message = "update completed"
                state["edge"] = "updating_to_completed"
            service.update_status.completed_at = now()
            state["new"] = service.update_status.state
            tx.update(service)

        self._status_update(cb, "mark update complete", service_id, state)

    def _status_update(self, cb, what: str, service_id: str,
                       state: Optional[dict] = None) -> None:
        """Run a status transaction; on success export the state gauge,
        the rollout edge timer, and the progress stamp (observability
        only fires for commits that actually happened)."""
        try:
            self.store.update(cb)
        except Exception:
            if self.threadless:
                raise   # sim: leadership loss must reach the control step
            log.exception("failed to %s", what)
            return
        if state is None:
            return
        if state.get("deleted"):
            # the service vanished mid-rollout: without this, the gauge
            # stays frozen at UPDATING and stuck_rollout fails forever
            # for a service that no longer exists
            _clear_state_gauge(service_id)
            return
        if state.get("new") is not None:
            _state_gauge(service_id, state["new"])
            if state.get("edge"):
                _edge_timer(state["edge"], now() - state.get("started", 0.0))
            # progress only for status writes that actually changed the
            # row: a no-oping callback (status already set) must not
            # keep a stuck rollout looking fresh to the stuck_rollout
            # health check
            _progress_gauge(service_id)
