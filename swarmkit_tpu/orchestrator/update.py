"""Rolling-update supervisor: one Updater per service, parallelism-bounded
workers over dirty slots, start-first/stop-first ordering, failure monitoring
with pause/rollback.

Reference: manager/orchestrator/update/updater.go.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as queue_mod
import threading
from typing import Dict, List, Optional

from ..models.objects import Cluster, Service, Task
from ..models.types import (
    TaskState, UpdateFailureAction, UpdateOrder, UpdateState, UpdateStatus,
    now,
)
from ..state.events import Event
from ..state.store import MemoryStore, WriteTx
from . import common
from .restart import Supervisor as RestartSupervisor

log = logging.getLogger("update")


def _specs_equal(a, b) -> bool:
    return a is b or dataclasses.asdict(a) == dataclasses.asdict(b)


class Supervisor:
    """Tracks at most one in-flight Updater per service
    (reference: updater.go:26)."""

    def __init__(self, store: MemoryStore, restarts: RestartSupervisor):
        self.store = store
        self.restarts = restarts
        self._mu = threading.Lock()
        self._updates: Dict[str, "Updater"] = {}

    def update(self, cluster: Optional[Cluster], service: Service,
               slots: List[common.Slot]) -> None:
        with self._mu:
            existing = self._updates.get(service.id)
            if existing is not None:
                if _specs_equal(service.spec, existing.new_service.spec):
                    return  # already working towards this goal
                # blocking cancel serializes updaters per service: the old
                # one must be fully out of its slots before the new one
                # touches them (reference: updater.go:56-61).  Safe under
                # _mu — the updater's done event fires before its cleanup
                # callback re-takes _mu.
                existing.cancel()
            updater = Updater(self.store, self.restarts, cluster, service)
            self._updates[service.id] = updater

        def run():
            updater.run(slots)
            with self._mu:
                if self._updates.get(service.id) is updater:
                    del self._updates[service.id]

        threading.Thread(target=run, name=f"updater-{service.id[:8]}",
                         daemon=True).start()

    def cancel_all(self) -> None:
        with self._mu:
            updates = list(self._updates.values())
        for u in updates:
            u.cancel()


class Updater:
    """Updates one service's slots to the new spec
    (reference: updater.go:85)."""

    def __init__(self, store: MemoryStore, restarts: RestartSupervisor,
                 cluster: Optional[Cluster], new_service: Service):
        self.store = store
        self.restarts = restarts
        self.cluster = cluster.copy() if cluster else None
        self.new_service = new_service.copy()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._mu = threading.Lock()
        self._updated_tasks: Dict[str, float] = {}  # id -> RUNNING stamp

    def cancel(self) -> None:
        self._stop.set()
        # must outlast _run's worker joins so per-service serialization
        # holds: a successor updater may not start while our workers can
        # still touch slots
        self._done.wait(timeout=30)

    # ----------------------------------------------------------------- run

    def run(self, slots: List[common.Slot]) -> None:
        try:
            self._run(slots)
        except Exception:
            log.exception("updater failed")
        finally:
            self._done.set()

    def _run(self, slots: List[common.Slot]) -> None:
        service = self.new_service
        us = service.update_status
        if us is not None and us.state in (UpdateState.PAUSED,
                                           UpdateState.ROLLBACK_PAUSED):
            return

        dirty_slots = [s for s in slots if self._is_slot_dirty(s)]
        if not dirty_slots:
            if us is not None and us.state in (UpdateState.UPDATING,
                                               UpdateState.ROLLBACK_STARTED):
                self._complete_update(service.id)
            return

        if us is None:
            self._start_update(service.id)

        rollback = us is not None and us.state == UpdateState.ROLLBACK_STARTED
        update_config = common.update_config_for(service, rollback)
        monitoring_period = update_config.monitor or 30.0

        parallelism = update_config.parallelism or len(dirty_slots)

        failed_tasks: set = set()
        self._total_failures = 0
        self._stopped = False
        n_dirty = len(dirty_slots)

        def failure_triggers_action(failed_task: Task) -> bool:
            if failed_task.id in failed_tasks:
                return False
            with self._mu:
                started_at = self._updated_tasks.get(failed_task.id)
            if started_at is None:
                return False
            if started_at and now() - started_at > monitoring_period:
                return False
            failed_tasks.add(failed_task.id)
            self._total_failures += 1
            if (self._total_failures / n_dirty
                    > update_config.max_failure_ratio):
                action = update_config.failure_action
                if action == UpdateFailureAction.PAUSE:
                    self._stopped = True
                    self._pause_update(
                        service.id,
                        "update paused due to failure or early termination "
                        f"of task {failed_task.id}")
                    return True
                if action == UpdateFailureAction.ROLLBACK:
                    if rollback:
                        # never roll back a rollback
                        self._pause_update(
                            service.id,
                            "rollback paused due to failure or early "
                            f"termination of task {failed_task.id}")
                        return True
                    self._stopped = True
                    self._rollback_update(
                        service.id,
                        "update rolled back due to failure or early "
                        f"termination of task {failed_task.id}")
                    return True
            return False

        watch_failures = (update_config.failure_action
                          != UpdateFailureAction.CONTINUE)
        failed_watch = None
        if watch_failures:
            sid = service.id

            def pred(ev):
                return (isinstance(ev, Event) and ev.action == "update"
                        and isinstance(ev.obj, Task)
                        and ev.obj.service_id == sid
                        and ev.obj.status.state > TaskState.RUNNING)

            failed_watch = self.store.queue.subscribe(
                pred, accepts_blocks=True)   # blocks are never failures

        try:
            slot_queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=1)
            workers = [threading.Thread(
                target=self._worker, args=(slot_queue, update_config),
                daemon=True) for _ in range(parallelism)]
            for w in workers:
                w.start()

            aborted = False
            for slot in dirty_slots:
                while not aborted:
                    if self._stop.is_set():
                        self._stopped = True
                        aborted = True
                        break
                    if failed_watch is not None:
                        try:
                            ev = failed_watch.get_nowait()
                            if failure_triggers_action(ev.obj):
                                aborted = True
                                break
                        except queue_mod.Empty:
                            pass
                        except Exception:
                            pass
                    try:
                        slot_queue.put(slot, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if aborted:
                    break

            # poison pills must always be delivered: workers only ever exit
            # by consuming one, so giving up on a Full queue would leave
            # them blocked in get() forever
            for _ in workers:
                while True:
                    try:
                        slot_queue.put(None, timeout=0.5)
                        break
                    except queue_mod.Full:
                        continue
            # workers must be fully out of their slots before the monitor
            # window / completion / a successor updater can run
            for w in workers:
                w.join(timeout=30)

            if not self._stopped and not self._stop.is_set():
                # monitor window before declaring completion
                if update_config.delay >= monitoring_period:
                    monitoring_period = update_config.delay + 1.0
                from ..state.watch import Closed
                deadline = now() + monitoring_period
                while now() < deadline:
                    if self._stop.is_set():
                        self._stopped = True
                        break
                    if failed_watch is None:
                        break
                    try:
                        ev = failed_watch.get(
                            timeout=min(0.2, deadline - now()))
                    except TimeoutError:
                        continue
                    except Closed:
                        break
                    if failure_triggers_action(ev.obj):
                        break

            if not self._stopped and not self._stop.is_set():
                self._complete_update(service.id)
        finally:
            if failed_watch is not None:
                self.store.queue.unsubscribe(failed_watch)

    # -------------------------------------------------------------- workers

    def _worker(self, slot_queue, update_config) -> None:
        while True:
            slot = slot_queue.get()
            if slot is None:
                return
            # the entire slot handling stays inside try: a worker that dies
            # without consuming its poison pill would wedge _run's pill
            # delivery loop forever
            try:
                running_task = None
                clean_task = None
                for t in slot:
                    if not self._is_task_dirty(t):
                        if t.desired_state == TaskState.RUNNING:
                            running_task = t
                            break
                        if t.desired_state < TaskState.RUNNING:
                            clean_task = t
                if running_task is not None:
                    self._use_existing_task(slot, running_task)
                elif clean_task is not None:
                    self._use_existing_task(slot, clean_task)
                else:
                    node_id = ""
                    if common.is_global_service(self.new_service):
                        node_id = slot[0].node_id
                    updated = common.new_task(
                        self.cluster, self.new_service, slot[0].slot, node_id)
                    updated.desired_state = TaskState.READY
                    self._update_task(slot, updated, update_config.order)
            except Exception:
                log.exception("update failed")
            if update_config.delay:
                # on stop, fall through to get() so we exit by consuming a
                # poison pill rather than stranding one in the queue
                self._stop.wait(timeout=update_config.delay)

    def _update_task(self, slot: common.Slot, updated: Task, order) -> None:
        """Atomically create the updated task and bring down the old one
        (reference: updater.go:367)."""
        uid = updated.id

        def pred(ev):
            return (isinstance(ev, Event) and isinstance(ev.obj, Task)
                    and ev.obj.id == uid and ev.action == "update")

        # accepts_blocks: this wait only cares about state>=RUNNING, which
        # assignment blocks (state<=RUNNING) never carry; the agent's
        # RUNNING flip arrives as a per-object event
        sub = self.store.queue.subscribe(pred, accepts_blocks=True)
        try:
            with self._mu:
                self._updated_tasks[uid] = 0.0

            start_then_stop = order == UpdateOrder.START_FIRST
            delay_done = None

            def txn(tx: WriteTx) -> None:
                nonlocal delay_done
                if tx.get(Service, updated.service_id) is None:
                    raise RuntimeError("service was deleted")
                tx.create(updated)
                if start_then_stop:
                    delay_done = self.restarts.delay_start(
                        None, uid, 0.0, False)
                else:
                    old_task = self._remove_old_tasks(tx, slot)
                    delay_done = self.restarts.delay_start(
                        old_task, uid, 0.0, True)

            self.store.update(txn)

            if delay_done is not None:
                while not delay_done.wait(timeout=0.2):
                    if self._stop.is_set():
                        return

            # wait for the new task to come up
            while True:
                if self._stop.is_set():
                    return
                try:
                    ev = sub.get(timeout=0.2)
                except TimeoutError:
                    continue
                except Exception:
                    return
                t = ev.obj
                if t.status.state >= TaskState.RUNNING:
                    with self._mu:
                        self._updated_tasks[uid] = now()
                    if start_then_stop and \
                            t.status.state == TaskState.RUNNING:
                        def rm(tx: WriteTx) -> None:
                            self._remove_old_tasks(tx, slot)
                        try:
                            self.store.update(rm)
                        except Exception:
                            log.exception("failed to remove old task after "
                                          "starting replacement")
                    return
        finally:
            self.store.queue.unsubscribe(sub)

    def _use_existing_task(self, slot: common.Slot, existing: Task) -> None:
        remove = [t for t in slot if t is not existing]
        if not remove and existing.desired_state == TaskState.RUNNING:
            return
        delay_done = None

        def txn(tx: WriteTx) -> None:
            nonlocal delay_done
            old_task = self._remove_old_tasks(tx, remove) if remove else None
            if existing.desired_state != TaskState.RUNNING:
                delay_done = self.restarts.delay_start(
                    old_task, existing.id, 0.0, True)

        self.store.update(txn)
        if delay_done is not None:
            while not delay_done.wait(timeout=0.2):
                if self._stop.is_set():
                    return

    def _remove_old_tasks(self, tx: WriteTx,
                          remove: common.Slot) -> Optional[Task]:
        """Shut down the given tasks; returns one that was shut down
        (reference: updater.go:493)."""
        removed = None
        for original in remove:
            if original.desired_state > TaskState.RUNNING:
                continue
            t = tx.get(Task, original.id)
            if t is None:
                continue
            if t.desired_state > TaskState.RUNNING:
                continue
            t = t.copy()
            t.desired_state = TaskState.SHUTDOWN
            tx.update(t)
            removed = t
        return removed

    # ------------------------------------------------------------ dirtiness

    def _is_task_dirty(self, t: Task) -> bool:
        from ..models.objects import Node
        n = self.store.raw_get(Node, t.node_id) if t.node_id else None
        return common.is_task_dirty(self.new_service, t, n)

    def _is_slot_dirty(self, slot: common.Slot) -> bool:
        return len(slot) > 1 or (len(slot) == 1
                                 and self._is_task_dirty(slot[0]))

    # -------------------------------------------------------- status writes

    def _start_update(self, service_id: str) -> None:
        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None or service.update_status is not None:
                return
            service = service.copy()
            service.update_status = UpdateStatus(
                state=UpdateState.UPDATING, started_at=now(),
                message="update in progress")
            tx.update(service)

        self._safe_update(cb, "mark update in progress")

    def _pause_update(self, service_id: str, message: str) -> None:
        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None or service.update_status is None:
                return
            service = service.copy()
            if service.update_status.state == UpdateState.ROLLBACK_STARTED:
                service.update_status.state = UpdateState.ROLLBACK_PAUSED
            else:
                service.update_status.state = UpdateState.PAUSED
            service.update_status.message = message
            tx.update(service)

        self._safe_update(cb, "pause update")

    def _rollback_update(self, service_id: str, message: str) -> None:
        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None or service.update_status is None:
                return
            service = service.copy()
            service.update_status.state = UpdateState.ROLLBACK_STARTED
            service.update_status.message = message
            if service.previous_spec is None:
                raise RuntimeError("cannot roll back service because no "
                                   "previous spec is available")
            service.spec = service.previous_spec
            service.spec_version = (service.previous_spec_version.copy()
                                    if service.previous_spec_version else None)
            service.previous_spec = None
            service.previous_spec_version = None
            tx.update(service)

        self._safe_update(cb, "start rollback")

    def _complete_update(self, service_id: str) -> None:
        def cb(tx: WriteTx) -> None:
            service = tx.get(Service, service_id)
            if service is None or service.update_status is None:
                return
            service = service.copy()
            if service.update_status.state == UpdateState.ROLLBACK_STARTED:
                service.update_status.state = UpdateState.ROLLBACK_COMPLETED
                service.update_status.message = "rollback completed"
            else:
                service.update_status.state = UpdateState.COMPLETED
                service.update_status.message = "update completed"
            service.update_status.completed_at = now()
            tx.update(service)

        self._safe_update(cb, "mark update complete")

    def _safe_update(self, cb, what: str) -> None:
        try:
            self.store.update(cb)
        except Exception:
            log.exception("failed to %s", what)
