from .sharded import NODE_AXIS, ShardedPlanFn, make_mesh, plan_group_sharded
