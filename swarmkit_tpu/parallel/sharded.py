"""Node-axis sharding of the scheduling kernel over a TPU mesh.

Scaling axis (SURVEY.md §5.7): the tasks×nodes problem is sharded over the
**node dimension** — each device owns N/D nodes' SoA arrays.  The kernel's
only cross-node dependencies are the water-level and tie-threshold binary
searches, whose per-iteration state is an [L]-vector of partial sums — so
the sharded kernel is the *same code* as the single-chip kernel with the
segment-sum reductions wrapped in a `psum` over the mesh axis.  Collective
traffic per group: ~120 psums of an [L]-vector (L = spread-branch count,
usually 1) — a few KB over ICI, independent of node count.

Design notes vs the reference: SwarmKit scales its scheduler by heap bounds
and batching in one Go process (design/scheduler.md); there is no
distributed scheduler to mirror.  This module is the TPU-native scaling
story: pjit/shard_map over a Mesh, XLA collectives over ICI, zero host
coordination inside a tick.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..ops.kernel import (
    FusedCarry, FusedGroups, FusedShared, FusedStrategy, GroupInputs,
    NodeInputs, StrategyInputs, plan_fused, plan_group, plan_strategy,
)

NODE_AXIS = "nodes"


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def mesh_from_env() -> Optional[Mesh]:
    """Build the planner mesh the SWARM_PLANNER_MESH knob asks for:
    an integer device count >1 selects the first D devices (D must be
    available — on CPU images use XLA_FLAGS
    --xla_force_host_platform_device_count).  Unset/1/garbage means
    single-device (no mesh); asking for more devices than exist is a
    loud no (misconfiguration must not silently run slower)."""
    import os
    raw = os.environ.get("SWARM_PLANNER_MESH", "").strip()
    if not raw:
        return None
    try:
        d = int(raw)
    except ValueError:
        return None
    if d <= 1:
        return None
    devices = jax.devices()
    if len(devices) < d:
        raise RuntimeError(
            f"SWARM_PLANNER_MESH={d} but only {len(devices)} device(s) "
            "available")
    return make_mesh(devices[:d])


# PartitionSpecs: node-dimension sharded, everything else replicated.
# quota_ok defaults to None here — specs must match the input pytree
# STRUCTURE, and the quota mask column is only materialized for
# quota-blocked groups (_node_specs switches the spec in per call).
_NODE_SPECS = NodeInputs(
    valid=P(NODE_AXIS), ready=P(NODE_AXIS), res_ok=P(NODE_AXIS),
    res_cap=P(NODE_AXIS), svc_tasks=P(NODE_AXIS),
    total_tasks=P(NODE_AXIS), failures=P(NODE_AXIS), leaf=P(NODE_AXIS),
    os_hash=P(None, NODE_AXIS), arch_hash=P(None, NODE_AXIS),
    port_conflict=P(NODE_AXIS), extra_mask=P(NODE_AXIS))


def _node_specs(nodes: NodeInputs) -> NodeInputs:
    if nodes.quota_ok is None:
        return _NODE_SPECS
    return _NODE_SPECS._replace(quota_ok=P(NODE_AXIS))

_GROUP_SPECS = GroupInputs(
    k=P(), con_hash=P(None, None, NODE_AXIS),
    con_op=P(), con_exp=P(), plat=P(), maxrep=P(), port_limited=P())


@functools.partial(jax.jit, static_argnames=("L", "mesh"))
def plan_group_sharded(nodes: NodeInputs, group: GroupInputs, L: int,
                       mesh: Mesh, hier=()):
    """Sharded group placement:
    (x i32[N] sharded, fail_counts i32[7], spill bool)."""

    n_devices = mesh.shape[NODE_AXIS]
    local_n = nodes.ready.shape[0] // n_devices

    def kernel(nodes_l: NodeInputs, group_l: GroupInputs, hier_l):
        reduce = lambda v: jax.lax.psum(v, NODE_AXIS)  # noqa: E731
        offset = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * local_n
        return plan_group(nodes_l, group_l, L, reduce=reduce,
                          idx_offset=offset, hier=hier_l)

    if hier:
        upper, leaf_parent = hier
        # node-dim segment columns shard with the nodes; the small
        # branch-level parent maps are replicated
        hier_specs = (tuple((P(NODE_AXIS), P()) for _ in upper), P())
    else:
        hier_specs = ()
    # check_rep=False: this jax version's replication checker mistypes the
    # scan carry inside psum-reducing kernels (mismatched replication
    # [None, set(), None] vs [None, set(), {'nodes'}]); the checker is
    # advisory — the collectives themselves are unchanged
    fn = shard_map(kernel, mesh=mesh,
                   in_specs=(_node_specs(nodes), _GROUP_SPECS,
                             hier_specs),
                   out_specs=(P(NODE_AXIS), P(), P()),
                   check_rep=False)
    return fn(nodes, group, hier)


# Strategy-kernel PartitionSpecs: the headroom columns shard with the
# nodes; the per-group weight vector and the learned-scorer parameter
# arrays are tiny and replicate.
_STRATEGY_SPECS = StrategyInputs(
    hr_cpu=P(NODE_AXIS), hr_mem=P(NODE_AXIS), hr_gen=P(NODE_AXIS),
    weights=P(), w1=P(), b1=P(), w2=P(), b2=P())


@functools.partial(jax.jit, static_argnames=("strategy", "mesh"))
def plan_strategy_sharded(nodes: NodeInputs, group: GroupInputs,
                          sin: StrategyInputs, strategy: int,
                          mesh: Mesh):
    """Sharded non-spread strategy placement: the same score + packfill
    / waterfill program as ops.kernel.plan_strategy with the node axis
    split over the mesh (psum reduce, per-shard index offset) —
    (x i32[N] sharded, fail_counts i32[8], spill bool=False)."""

    n_devices = mesh.shape[NODE_AXIS]
    local_n = nodes.ready.shape[0] // n_devices

    def kernel(nodes_l: NodeInputs, group_l: GroupInputs,
               sin_l: StrategyInputs):
        reduce = lambda v: jax.lax.psum(v, NODE_AXIS)  # noqa: E731
        offset = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * local_n
        return plan_strategy(nodes_l, group_l, sin_l, strategy,
                             reduce=reduce, idx_offset=offset)

    # check_rep=False: same advisory-checker mistyping as
    # plan_group_sharded (fori_loop carries inside psum kernels)
    fn = shard_map(kernel, mesh=mesh,
                   in_specs=(_node_specs(nodes), _GROUP_SPECS,
                             _STRATEGY_SPECS),
                   out_specs=(P(NODE_AXIS), P(), P()),
                   check_rep=False)
    return fn(nodes, group, sin)


# Fused-batch PartitionSpecs: node-dimension sharded, group/service
# axes replicated (G and S are small; the node axis is the scale axis).
_FUSED_SHARED_SPECS = FusedShared(
    valid=P(NODE_AXIS), ready=P(NODE_AXIS), os_hash=P(None, NODE_AXIS),
    arch_hash=P(None, NODE_AXIS), svc0=P(None, NODE_AXIS))

_FUSED_GROUP_SPECS = FusedGroups(
    k=P(), slot=P(), maxrep=P(), cpu_d=P(), mem_d=P(),
    con_hash=P(None, None, None, NODE_AXIS), con_op=P(), con_exp=P(),
    plat=P(), failures=P(None, NODE_AXIS), leaf=P(None, NODE_AXIS),
    extra_mask=P(None, NODE_AXIS))


def _fused_group_specs(groups: FusedGroups) -> FusedGroups:
    if groups.quota_ok is None:
        return _FUSED_GROUP_SPECS
    return _FUSED_GROUP_SPECS._replace(quota_ok=P(None, NODE_AXIS))

_FUSED_CARRY_SPECS = FusedCarry(
    total=P(NODE_AXIS), cpu=P(NODE_AXIS), mem=P(NODE_AXIS),
    svc_acc=P(None, NODE_AXIS))

# Mixed-strategy fused runs: the per-group ids/weights and the
# run-wide learned parameters are all node-independent — replicated.
_FUSED_STRAT_SPECS = FusedStrategy(
    sid=P(), weights=P(), w1=P(), b1=P(), w2=P(), b2=P())


@functools.partial(jax.jit, static_argnames=("L", "mesh"))
def plan_fused_sharded(shared: FusedShared, groups: FusedGroups,
                       carry: FusedCarry, L: int, mesh: Mesh,
                       strat: Optional[FusedStrategy] = None):
    """Sharded fused batch: the same scan-over-groups program as
    ops.kernel.plan_fused with the node axis split over the mesh.
    Cross-shard traffic per group is unchanged from the per-group
    sharded kernel (~120 psums of an [L]-vector per scan step); the
    carry stays sharded across chunked calls, so chunk i+1 consumes
    chunk i's device-resident state with zero host round-trips.
    ``strat`` fuses binpack/weighted/learned groups into the same
    sharded scan (ops.kernel.plan_fused's in-scan strategy switch);
    None keeps the spread-only signature untouched."""

    n_devices = mesh.shape[NODE_AXIS]
    local_n = shared.valid.shape[0] // n_devices

    def kernel(shared_l, groups_l, carry_l, strat_l):
        reduce = lambda v: jax.lax.psum(v, NODE_AXIS)  # noqa: E731
        offset = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * local_n
        return plan_fused(shared_l, groups_l, carry_l, L, reduce=reduce,
                          idx_offset=offset, strat=strat_l)

    # check_rep=False: same advisory-checker mistyping as
    # plan_group_sharded above (scan carries inside psum kernels)
    fn = shard_map(kernel, mesh=mesh,
                   in_specs=(_FUSED_SHARED_SPECS,
                             _fused_group_specs(groups),
                             _FUSED_CARRY_SPECS,
                             _FUSED_STRAT_SPECS if strat is not None
                             else None),
                   out_specs=(P(None, NODE_AXIS), P(), P(),
                              _FUSED_CARRY_SPECS),
                   check_rep=False)
    return fn(shared, groups, carry, strat)


# ------------------------------------------------ sharded resident tier
#
# The streaming planner's device tier (ops/streaming.ResidentState) on a
# mesh: the five node-state columns live as node-axis-sharded arrays,
# dirty rows are bucketed by owning shard host-side and scattered by a
# per-shard donated program, and the wide-delta re-upload stages each
# device's slice directly via NamedSharding device_put.  The node bucket
# must divide evenly over the mesh (pow2 buckets/mesh sizes guarantee
# it); ResidentState falls back to the single-device tier otherwise.

#: resident node-state column layout (each of the five 1-D columns)
RESIDENT_SPEC = P(NODE_AXIS)
#: staged scatter-buffer layout: leading shard axis [D, db]
SCATTER_SPEC = P(NODE_AXIS, None)


def put_resident(cols, mesh: Mesh) -> tuple:
    """Mesh placement of resident columns: ``device_put`` with a
    node-axis NamedSharding ships each device its own slice (per-shard
    staging — no replicate-then-slice round trip)."""
    s = NamedSharding(mesh, RESIDENT_SPEC)
    # placement shim: the caller (streaming._device_upload) notes these
    # bytes under its resync-reason label — noting here too would
    # double-count the ledger
    # swarmlint: disable=device-path-purity
    return tuple(jax.device_put(a, s) for a in cols)


def put_scatter_updates(bufs, mesh: Mesh) -> tuple:
    """Mesh placement of the staged [D, db] dirty-row buffers: the
    leading axis is the shard axis, so each device receives only its
    own update rows."""
    s = NamedSharding(mesh, SCATTER_SPEC)
    # placement shim: the caller (streaming._device_sync) notes the
    # staged bytes under the shard_scatter label — noting here too
    # would double-count the ledger
    # swarmlint: disable=device-path-purity
    return tuple(jax.device_put(a, s) for a in bufs)


@functools.partial(jax.jit, static_argnames=("mesh",),
                   donate_argnums=(0, 1, 2, 3, 4))
def scatter_rows_sharded(valid, ready, cpu, mem, total, idx,
                         u_valid, u_ready, u_cpu, u_mem, u_total,
                         mesh: Mesh):
    """Per-shard donated dirty-row scatter — the mesh twin of
    ops.streaming._scatter_rows_jit.  The five resident columns are
    DONATED (XLA updates each shard's buffer in place); ``idx`` and the
    update buffers carry a leading shard axis [D, db] with LOCAL row
    indices (row % local_n, bucketed host-side by row // local_n; pad
    slots carry local_n, out of bounds, and drop).  Each device touches
    only rows it owns: zero cross-device traffic per sync."""

    def kernel(valid_l, ready_l, cpu_l, mem_l, total_l, idx_l,
               uv, ur, uc, um, ut):
        kw = dict(mode="drop")
        i = idx_l[0]
        return (valid_l.at[i].set(uv[0], **kw),
                ready_l.at[i].set(ur[0], **kw),
                cpu_l.at[i].set(uc[0], **kw),
                mem_l.at[i].set(um[0], **kw),
                total_l.at[i].set(ut[0], **kw))

    fn = shard_map(kernel, mesh=mesh,
                   in_specs=(RESIDENT_SPEC,) * 5 + (SCATTER_SPEC,) * 6,
                   out_specs=(RESIDENT_SPEC,) * 5,
                   check_rep=False)
    return fn(valid, ready, cpu, mem, total, idx,
              u_valid, u_ready, u_cpu, u_mem, u_total)


class ShardedPlanFn:
    """Drop-in ``plan_fn`` for ops.planner.TPUPlanner running on a mesh.

    Pads the node axis to a multiple of the mesh size and places inputs with
    NamedShardings so XLA keeps arrays device-resident between calls.
    """

    #: the fused path may route non-spread strategy groups through
    #: ``fused(..., strat=...)`` (ops.fusedbatch.probe_group checks)
    supports_strategies = True

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh or make_mesh()

    def __call__(self, nodes: NodeInputs, group: GroupInputs, L: int,
                 hier=()):
        d = self.mesh.shape[NODE_AXIS]
        n = nodes.ready.shape[0]
        if n % d:
            pad = d - n % d

            def pad_last(a):
                if a is None:   # absent quota mask column
                    return None
                width = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
                return np.pad(np.asarray(a), width)

            nodes = NodeInputs(*[pad_last(a) for a in nodes])
            group = group._replace(con_hash=pad_last(group.con_hash))
            if hier:
                upper, leaf_parent = hier
                hier = (tuple((pad_last(seg), parent)
                              for seg, parent in upper), leaf_parent)
        return plan_group_sharded(nodes, group, L, self.mesh, hier)

    def strategy(self, nodes: NodeInputs, group: GroupInputs,
                 sin: StrategyInputs, sid: int):
        """Sharded non-spread strategy dispatch (the planner's
        ``plan_strategy_jit`` twin).  Node-axis padding mirrors
        ``__call__``: padded rows carry valid=False, so their capacity
        is zero and their (arbitrary) strategy scores never place."""
        d = self.mesh.shape[NODE_AXIS]
        n = nodes.ready.shape[0]
        if n % d:
            pad = d - n % d

            def pad_last(a):
                if a is None:
                    return None
                width = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
                return np.pad(np.asarray(a), width)

            nodes = NodeInputs(*[pad_last(a) for a in nodes])
            group = group._replace(con_hash=pad_last(group.con_hash))
            sin = sin._replace(hr_cpu=pad_last(sin.hr_cpu),
                               hr_mem=pad_last(sin.hr_mem),
                               hr_gen=pad_last(sin.hr_gen))
        return plan_strategy_sharded(nodes, group, sin, sid, self.mesh)

    # ------------------------------------------------------- fused batch

    def _shard(self, value, specs):
        from ..obs import devicetelemetry as _devtel
        put = jax.device_put
        staged = [np.asarray(a) for a in value]
        _devtel.note_h2d("mesh_reshard", _devtel.tree_nbytes(staged))
        return type(value)(*(
            put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(staged, specs)))

    def prepare_fused(self, shared: FusedShared, carry: FusedCarry,
                      resident=None):
        """Place a fused run's node state on the mesh once, so every
        chunked dispatch reads device-resident shards instead of
        re-transferring the resource matrices per call.  The node
        bucket must divide evenly over the mesh (power-of-two buckets
        and mesh sizes guarantee it — asserted, not padded, because
        fused idx tie-keys must match the single-device program).

        ``resident`` (streaming fast path): the five node-state columns
        as ALREADY-mesh-sharded device arrays (ResidentState's sharded
        tier, node-axis layout).  The run seeds valid/ready and the
        resource carry from them with zero cross-device reshuffle —
        only the small per-run extras (platform hashes, service bases,
        the svc accumulator) transfer."""
        n = shared.valid.shape[0]
        d = self.mesh.shape[NODE_AXIS]
        if n % d:
            raise ValueError(
                f"fused node bucket {n} not divisible by mesh size {d}")
        if resident is not None:
            from ..obs import devicetelemetry as _devtel
            d_valid, d_ready, d_cpu, d_mem, d_total = resident
            put = jax.device_put
            extras = [np.asarray(a) for a in
                      (shared.os_hash, shared.arch_hash, shared.svc0,
                       carry.svc_acc)]
            _devtel.note_h2d("mesh_reshard", _devtel.tree_nbytes(extras))
            row_spec = NamedSharding(self.mesh, P(None, NODE_AXIS))
            os_h, arch_h, svc0, svc_acc = (put(a, row_spec)
                                           for a in extras)
            return (FusedShared(valid=d_valid, ready=d_ready,
                                os_hash=os_h, arch_hash=arch_h,
                                svc0=svc0),
                    FusedCarry(total=d_total, cpu=d_cpu, mem=d_mem,
                               svc_acc=svc_acc))
        return (self._shard(shared, _FUSED_SHARED_SPECS),
                self._shard(carry, _FUSED_CARRY_SPECS))

    def fused(self, shared: FusedShared, groups: FusedGroups,
              carry: FusedCarry, L: int,
              strat: Optional[FusedStrategy] = None):
        return plan_fused_sharded(shared, groups, carry, L, self.mesh,
                                  strat)
