"""Offline raft state inspection and repair for manager state dirs.

Reference: swarmd/cmd/swarm-rafttool (dump.go decrypt/dump commands,
main.go downgrade-key, renewcert.go) — offline WAL/snapshot decrypt &
dump, key downgrade, and certificate renewal for debugging and disaster
recovery.

Usage (module or CLI):
    python -m swarmkit_tpu.rafttool dump-wal <state-dir> [unlock-key]
    python -m swarmkit_tpu.rafttool dump-snapshot <state-dir> [unlock-key]
    python -m swarmkit_tpu.rafttool dump-object <state-dir> <collection>
    python -m swarmkit_tpu.rafttool decrypt <state-dir> <out-dir> [key]
    python -m swarmkit_tpu.rafttool downgrade-key <state-dir> <unlock-key>
    python -m swarmkit_tpu.rafttool renew-certs <state-dir> [unlock-key]

``state-dir`` may be a swarmd manager state directory (encrypted WAL
under the persisted CA key, optionally autolock-sealed — pass the
operator's unlock key) or a bare raft logger directory (plaintext).
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

from .state import serde
from .state.raft.storage import KeyEncoder, RaftLogger


def _open_logger(state_dir: str, unlock_key: str = "") -> RaftLogger:
    """A decoding RaftLogger for either a swarmd manager state dir
    (encrypted under the persisted CA key) or a bare logger dir."""
    state = _load_state(state_dir, unlock_key)
    if state is not None:
        # prev_ca_key present = a crash interrupted a CA-rotation re-key:
        # some records may still be sealed under the old key, exactly the
        # disaster this tool exists for (mirrors swarmd's own loader)
        prev = state.get("prev_ca_key")
        return RaftLogger(
            os.path.join(state_dir, "raft"),
            encoder=KeyEncoder(state["ca_key"],
                               fallback=KeyEncoder(prev) if prev
                               else None))
    return RaftLogger(state_dir)


def _load_state(state_dir: str, unlock_key: str = ""):
    """The swarmd manager-state record, or None for bare logger dirs
    (raises on a sealed state without the right unlock key)."""
    if not os.path.exists(os.path.join(state_dir, "manager-state.json")):
        return None
    from .swarmd import Swarmd
    probe = Swarmd.__new__(Swarmd)
    probe.state_dir = state_dir
    probe.unlock_key = unlock_key
    probe.raft_id = ""
    state = probe._load_manager_state()
    if state is not None:
        state["raft_id"] = probe.raft_id   # loader restored it
    return state


def dump_wal(state_dir: str, unlock_key: str = "") -> List[dict]:
    """Decoded WAL records: hard-state changes and entries with their
    store actions."""
    logger = _open_logger(state_dir, unlock_key)
    hs, entries = logger.read_wal()
    out: List[dict] = []
    if hs is not None:
        out.append({"type": "hardstate", "term": hs.term,
                    "vote": hs.voted_for, "commit": hs.commit})
    for e in entries:
        rec = {"type": "entry", "index": e.index, "term": e.term}
        if e.type != 0:
            rec["entry_type"] = "noop"
        elif e.data:
            try:
                # the shared entry grammar: binary columnar task blocks
                # (serde.BLOCK_ENTRY_MAGIC) and JSON change lists both
                # decode through the same seam the apply paths use
                actions = serde.entry_to_actions(e.data)
                rec["actions"] = [
                    {"action": "task_block",
                     "collection": "tasks",
                     "items": len(a.ids),
                     "base_version": a.base_version}
                    if a.action == "task_block" else
                    {"action": a.action, "collection": a.obj.collection,
                     "id": a.obj.id}
                    for a in actions]
            except Exception:
                rec["actions"] = "<undecodable>"
        out.append(rec)
    return out


def dump_snapshot(state_dir: str, unlock_key: str = "") -> Optional[dict]:
    """Snapshot summary: index/term + object counts per collection."""
    logger = _open_logger(state_dir, unlock_key)
    snap = logger.load_snapshot()
    if snap is None:
        return None
    summary = {"index": snap.index, "term": snap.term}
    if snap.data:
        payload = json.loads(snap.data)
        summary["store_version"] = payload.get("version")
        summary["objects"] = {
            coll: len(objs)
            for coll, objs in payload.get("tables", {}).items() if objs}
    return summary


def dump_objects(state_dir: str, collection: str,
                 unlock_key: str = "") -> List[dict]:
    """Full decoded objects of one collection from the snapshot."""
    logger = _open_logger(state_dir, unlock_key)
    snap = logger.load_snapshot()
    if snap is None or not snap.data:
        return []
    payload = json.loads(snap.data)
    return payload.get("tables", {}).get(collection, [])


def decrypt(state_dir: str, out_dir: str, unlock_key: str = "") -> None:
    """Write a PLAINTEXT copy of the WAL + snapshot to ``out_dir``
    (reference: rafttool decrypt) — for inspection with external tools.
    The output holds the cluster's full state unencrypted; handle it like
    the key material itself."""
    src = _open_logger(state_dir, unlock_key)
    hs, entries = src.read_wal()
    snap = src.load_snapshot()
    os.makedirs(out_dir, exist_ok=True)
    dst = RaftLogger(out_dir)   # no encoder: plaintext
    if snap is not None:
        dst.save_snapshot(snap, snap.index)
    dst.rewrite(hs, entries)


def downgrade_key(state_dir: str, unlock_key: str) -> None:
    """Unseal an autolocked manager state file so the daemon can start
    without the unlock key (reference: rafttool downgrade-key)."""
    state = _load_state(state_dir, unlock_key)
    if state is None:
        raise SystemExit(f"{state_dir} has no manager state file")
    payload = json.dumps({
        "raft_id": state.get("raft_id", ""),
        "ca_key": state["ca_key"].hex(),
        "ca_cert": state["ca_cert"].hex(),
        "prev_ca_key": state["prev_ca_key"].hex()
        if state.get("prev_ca_key") else "",
        "raft_port": state["raft_port"],
        "api_port": state.get("api_port", 0),
    }).encode()
    path = os.path.join(state_dir, "manager-state.json")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)


def renew_certs(state_dir: str, unlock_key: str = "") -> str:
    """Offline node-certificate renewal from the locally persisted CA —
    disaster recovery for a manager whose certs expired while the cluster
    was down (reference: rafttool renewcert.go)."""
    from .security import RootCA
    from .security.ca import KeyReadWriter

    state = _load_state(state_dir, unlock_key)
    if state is None:
        raise SystemExit(f"{state_dir} has no manager state file")
    ca = RootCA(state["ca_key"], state["ca_cert"])
    rw = KeyReadWriter(os.path.join(state_dir, "certificates", "node.key"))
    try:
        cert, _ = rw.read()
    except FileNotFoundError:
        raise SystemExit(
            f"{state_dir} has no node certificate to renew (the daemon "
            "re-issues one on next start from its join token)")
    fresh = ca.issue(cert.node_id, cert.role)
    rw.write(fresh, b"")
    return cert.node_id


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        return 2
    cmd, state_dir = argv[0], argv[1]
    if cmd == "dump-wal":
        for rec in dump_wal(state_dir, *(argv[2:3])):
            print(json.dumps(rec, sort_keys=True))
        return 0
    if cmd == "dump-snapshot":
        print(json.dumps(dump_snapshot(state_dir, *(argv[2:3])),
                         sort_keys=True, indent=2))
        return 0
    if cmd == "dump-object":
        if len(argv) < 3:
            print("usage: dump-object <state-dir> <collection>")
            return 2
        for obj in dump_objects(state_dir, argv[2], *(argv[3:4])):
            print(json.dumps(obj, sort_keys=True))
        return 0
    if cmd == "decrypt":
        if len(argv) < 3:
            print("usage: decrypt <state-dir> <out-dir> [unlock-key]")
            return 2
        decrypt(state_dir, argv[2], *(argv[3:4]))
        return 0
    if cmd == "downgrade-key":
        if len(argv) < 3:
            print("usage: downgrade-key <state-dir> <unlock-key>")
            return 2
        downgrade_key(state_dir, argv[2])
        return 0
    if cmd == "renew-certs":
        nid = renew_certs(state_dir, *(argv[2:3]))
        print(f"renewed certificate for {nid}")
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":   # pragma: no cover
    sys.exit(main())
