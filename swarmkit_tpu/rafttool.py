"""Offline raft state inspection: decode and dump WAL entries and
snapshots from a manager state directory.

Reference: swarmd/cmd/swarm-rafttool (dump.go) — offline WAL/snapshot
decrypt & dump for debugging and disaster recovery.

Usage (module or CLI):
    python -m swarmkit_tpu.rafttool dump-wal <state-dir>
    python -m swarmkit_tpu.rafttool dump-snapshot <state-dir>
    python -m swarmkit_tpu.rafttool dump-object <state-dir> <collection>
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from .state import serde
from .state.raft.storage import RaftLogger


def dump_wal(state_dir: str) -> List[dict]:
    """Decoded WAL records: hard-state changes and entries with their
    store actions."""
    logger = RaftLogger(state_dir)
    hs, entries = logger.read_wal()
    out: List[dict] = []
    if hs is not None:
        out.append({"type": "hardstate", "term": hs.term,
                    "vote": hs.voted_for, "commit": hs.commit})
    for e in entries:
        rec = {"type": "entry", "index": e.index, "term": e.term}
        if e.type != 0:
            rec["entry_type"] = "noop"
        elif e.data:
            try:
                actions = serde.loads_dict(e.data)
                rec["actions"] = [
                    {"action": a["action"], "collection": a["collection"],
                     "id": a["obj"].get("id", "")}
                    for a in actions]
            except Exception:
                rec["actions"] = "<undecodable>"
        out.append(rec)
    return out


def dump_snapshot(state_dir: str) -> Optional[dict]:
    """Snapshot summary: index/term + object counts per collection."""
    logger = RaftLogger(state_dir)
    snap = logger.load_snapshot()
    if snap is None:
        return None
    summary = {"index": snap.index, "term": snap.term}
    if snap.data:
        payload = json.loads(snap.data)
        summary["store_version"] = payload.get("version")
        summary["objects"] = {
            coll: len(objs)
            for coll, objs in payload.get("tables", {}).items() if objs}
    return summary


def dump_objects(state_dir: str, collection: str) -> List[dict]:
    """Full decoded objects of one collection from the snapshot."""
    logger = RaftLogger(state_dir)
    snap = logger.load_snapshot()
    if snap is None or not snap.data:
        return []
    payload = json.loads(snap.data)
    return payload.get("tables", {}).get(collection, [])


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        return 2
    cmd, state_dir = argv[0], argv[1]
    if cmd == "dump-wal":
        for rec in dump_wal(state_dir):
            print(json.dumps(rec, sort_keys=True))
        return 0
    if cmd == "dump-snapshot":
        print(json.dumps(dump_snapshot(state_dir), sort_keys=True,
                         indent=2))
        return 0
    if cmd == "dump-object":
        if len(argv) < 3:
            print("usage: dump-object <state-dir> <collection>")
            return 2
        for obj in dump_objects(state_dir, argv[2]):
            print(json.dumps(obj, sort_keys=True))
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":   # pragma: no cover
    sys.exit(main())
