"""Remotes tracker + connection broker: weighted manager-peer selection.

Reference: remotes/remotes.go (observation-based weights) and
connectionbroker/broker.go (local vs remote pick).

Agents track the set of known managers; every successful interaction
raises a peer's weight toward the maximum, every failure collapses it
toward the minimum, and selection samples proportionally to weight — so
traffic drains away from flapping managers without ever blacklisting them
completely (they can recover).
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Dict, List, Optional, Tuple

from .utils.metrics import registry as _metrics

log = logging.getLogger("remotes")

#: error ``code`` attributes that mean "the session died but the link is
#: healthy" — the failover client re-resolves to a DIFFERENT manager
#: instead of hammering the one that just invalidated the session
SESSION_ERROR_CODES = ("session_invalid", "node_not_registered")


def count_reconnect(reason: str) -> None:
    """One agent reconnect cause, by reason — the weighted-remotes
    observability counter (`swarm_agent_reconnects{reason=}`)."""
    _metrics.counter(f'swarm_agent_reconnects{{reason="{reason}"}}')

# reference: remotes.go DefaultObservationWeight and bounds
DEFAULT_OBSERVATION_WEIGHT = 10
REMOTE_WEIGHT_MAX = 1 << 8
REMOTE_WEIGHT_MIN = -(1 << 8)

# reconnect backoff ladder (agent sessions; reference: agent.go's
# session backoff, hardened with full jitter per the AWS exponential
# backoff guidance so a mass disconnect does not reconnect in lockstep)
RECONNECT_BACKOFF_BASE = 0.1
RECONNECT_BACKOFF_CAP = 8.0

Addr = Tuple[str, int]


def backoff_with_jitter(attempt: int,
                        rng: Optional[random.Random] = None,
                        base: float = RECONNECT_BACKOFF_BASE,
                        cap: float = RECONNECT_BACKOFF_CAP) -> float:
    """Jittered exponential backoff: with ``ceiling = min(cap,
    base * 2^attempt)``, the delay is drawn uniformly from
    ``[0.1 * ceiling, ceiling]`` — AWS-style full jitter, floored at a
    tenth of the ceiling so a long backoff can never collapse into a
    hot reconnect loop.

    ``attempt`` counts consecutive failures starting at 0.  The ceiling
    caps at ``cap`` however large ``attempt`` grows (no overflow: the
    exponent is clamped first).  Drawing through an injected ``rng``
    keeps simulated reconnect storms deterministic per seed while still
    de-synchronizing the fleet: two agents sharing a failure instant
    draw different delays from their own streams.
    """
    rng = rng or random
    ceiling = min(cap, base * (2.0 ** min(attempt, 30)))
    # avoid a zero sleep (a hot reconnect loop) while keeping the
    # spread: the floor is a tenth of the current ceiling
    return ceiling * (0.1 + 0.9 * rng.random())


class NoSuchRemote(Exception):
    pass


class Remotes:
    def __init__(self, *addrs: Addr, rng: Optional[random.Random] = None):
        self._mu = threading.Lock()
        self._weights: Dict[Addr, int] = {
            tuple(a): DEFAULT_OBSERVATION_WEIGHT for a in addrs}
        # injectable rng seam: deterministic peer selection in the sim
        self._rng = rng or random.Random()

    def observe(self, addr: Addr, weight: int = DEFAULT_OBSERVATION_WEIGHT
                ) -> None:
        """Positive observations move toward max, negative toward min
        (reference: remotes.go Observe / ObserveIfExists)."""
        addr = tuple(addr)
        with self._mu:
            if addr not in self._weights and weight < 0:
                # ObserveIfExists semantics: a failure against a peer we
                # no longer track (e.g. just removed after demotion) must
                # not resurrect it into the selection pool
                return
            cur = self._weights.get(addr, 0)
            if weight >= 0:
                self._weights[addr] = min(
                    REMOTE_WEIGHT_MAX, cur + weight)
            else:
                self._weights[addr] = max(
                    REMOTE_WEIGHT_MIN, cur + weight)

    def remove(self, addr: Addr) -> None:
        with self._mu:
            self._weights.pop(tuple(addr), None)

    def weights(self) -> Dict[Addr, int]:
        with self._mu:
            return dict(self._weights)

    def select(self, *excludes: Addr) -> Addr:
        """Weighted random pick (reference: remotes.go Select)."""
        excluded = {tuple(e) for e in excludes}
        with self._mu:
            candidates = [(a, w) for a, w in self._weights.items()
                          if a not in excluded]
            if not candidates:
                raise NoSuchRemote("no remote managers available")
            # shift weights positive; +1 keeps dead peers selectable so
            # they can recover
            lowest = min(w for _, w in candidates)
            total = sum(w - lowest + 1 for _, w in candidates)
            pick = self._rng.uniform(0, total)
            acc = 0.0
            for addr, w in candidates:
                acc += w - lowest + 1
                if pick <= acc:
                    return addr
            return candidates[-1][0]


class PersistentRemotes(Remotes):
    """Remotes whose peer set survives restarts (reference:
    node/node.go:1202 persistentRemotes + state.json): every membership
    change rewrites the state file atomically, and construction merges
    the persisted peers with any seed addresses — so a restarted worker
    can reach the cluster even when its original --join-addr is gone."""

    def __init__(self, path: str, *addrs: Addr,
                 rng: Optional[random.Random] = None):
        self._path = path
        # file writes serialize separately from the weights lock: the
        # session loop and the log shipper can both trigger membership
        # saves concurrently
        self._save_mu = threading.Lock()
        super().__init__(*addrs, rng=rng)
        for addr in self._load():
            if tuple(addr) not in self._weights:
                self._weights[tuple(addr)] = DEFAULT_OBSERVATION_WEIGHT
        self._save()

    def _load(self) -> List[Addr]:
        import json
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if not isinstance(data, dict):
                return []
            return [tuple(a) for a in data.get("managers", [])]
        except (OSError, ValueError, TypeError):
            # unreadable or corrupt state file: fall back to the seeds,
            # mirroring _save's tolerance
            return []

    def _save(self) -> None:
        import json
        import os as _os
        with self._save_mu:
            tmp = self._path + ".tmp"
            try:
                _os.makedirs(_os.path.dirname(self._path) or ".",
                             exist_ok=True)
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"managers": sorted(
                        list(a) for a in self.weights())}, f)
                _os.replace(tmp, self._path)
            except OSError:
                log.exception("persisting remotes failed")

    def observe(self, addr: Addr,
                weight: int = DEFAULT_OBSERVATION_WEIGHT) -> None:
        known = tuple(addr) in self.weights()
        super().observe(addr, weight)
        if not known and tuple(addr) in self.weights():
            self._save()   # membership change, not just a weight shift

    def remove(self, addr: Addr) -> None:
        known = tuple(addr) in self.weights()
        super().remove(addr)
        if known:
            self._save()


class ConnectionBroker:
    """Picks a manager connection for CA/dispatcher clients: the local
    manager when this node runs one, a weighted remote otherwise
    (reference: connectionbroker/broker.go)."""

    def __init__(self, remotes: Remotes, local_addr: Optional[Addr] = None):
        self.remotes = remotes
        self.local_addr = tuple(local_addr) if local_addr else None

    def select(self, prefer_local: bool = True, *excludes: Addr) -> Addr:
        if prefer_local and self.local_addr is not None:
            return self.local_addr
        try:
            return self.remotes.select(*excludes)
        except NoSuchRemote:
            if excludes:
                return self.remotes.select()  # everything failed: any
            raise

    def observe_success(self, addr: Addr) -> None:
        self.remotes.observe(addr, DEFAULT_OBSERVATION_WEIGHT)

    def observe_failure(self, addr: Addr) -> None:
        self.remotes.observe(addr, -DEFAULT_OBSERVATION_WEIGHT)


class FailoverDispatcherClient:
    """Dispatcher-surface client that fails over between managers using
    the broker: each call picks the current remote; errors down-weight it
    and the next call tries another (the agent's session loop handles the
    re-registration)."""

    def __init__(self, broker: ConnectionBroker, certificate,
                 client_factory=None):
        from .net.client import RemoteDispatcherClient
        self.broker = broker
        self.certificate = certificate
        self._factory = client_factory or (
            lambda addr: RemoteDispatcherClient(addr, self.certificate))
        self._mu = threading.Lock()
        self._current: Optional[Addr] = None
        self._client = None
        self._last_failed: Optional[Addr] = None

    def _get(self):
        with self._mu:
            if self._client is None:
                excludes = (self._last_failed,) if self._last_failed \
                    else ()
                self._current = self.broker.select(
                    False, *excludes)
                self._client = self._factory(self._current)
            return self._current, self._client

    def _rotate(self, addr: Addr) -> None:
        """Drop the cached client so the next call picks a different
        manager (does not itself touch health weights)."""
        with self._mu:
            self._last_failed = addr   # next pick avoids this peer
            if self._current == addr:
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None
                self._current = None

    def _fail(self, addr: Addr) -> None:
        self.broker.observe_failure(addr)
        self._rotate(addr)

    def _call(self, method: str, *args, **kwargs):
        addr, client = self._get()
        try:
            result = getattr(client, method)(*args, **kwargs)
            self.broker.observe_success(addr)
            # heartbeat responses piggyback the live manager list: add
            # newcomers so we can fail over to managers that joined after
            # we did, and prune departed ones so removed/demoted managers
            # stop receiving failover picks (reference: session
            # Message.Managers drives the agent's remotes the same way)
            managers = getattr(client, "last_managers", None)
            if managers:
                desired = {tuple(m) for m in managers}
                tracked = self.broker.remotes.weights()
                for m in desired - set(tracked):
                    self.broker.remotes.observe(
                        m, DEFAULT_OBSERVATION_WEIGHT)
                for m in set(tracked) - desired:
                    self.broker.remotes.remove(m)
            return result
        except (ConnectionError, OSError, TimeoutError):
            # only transport failures indict the manager's health;
            # application errors (invalid session etc.) travelled over a
            # perfectly healthy link and must not shift weights
            self._fail(addr)
            raise
        except Exception as e:
            from .net.client import NotLeader
            if isinstance(e, NotLeader):
                # a healthy follower: rotate to another manager without
                # down-weighting it (it may become leader any moment)
                self._rotate(addr)
            elif getattr(e, "code", "") in SESSION_ERROR_CODES:
                # the session is gone (manager teardown, failover hand-
                # off): the next register goes to a DIFFERENT member —
                # re-registering with the invalidator just races its
                # teardown.  No health down-weight: the link was fine.
                self._rotate(addr)
            raise

    def note_session_failure(self) -> None:
        """Agent-side hook for session failures the call path could not
        classify (assignment stream closed server-side): rotate off the
        current manager so the re-register lands elsewhere."""
        with self._mu:
            cur = self._current
        if cur is not None:
            self._rotate(cur)

    def register(self, node_id, description=None):
        return self._call("register", node_id, description=description)

    def heartbeat(self, node_id, session_id):
        return self._call("heartbeat", node_id, session_id)

    @property
    def network_key_delivery(self):
        """Heartbeat piggyback stash (network bootstrap keys) as one
        atomic (clock, keys) pair from whichever inner wire client served
        the last heartbeat — a single locked read so a concurrent
        failover rotation cannot tear the pair apart."""
        with self._mu:
            c = self._client
            if c is None:
                return None, None
            return (getattr(c, "last_key_clock", None),
                    getattr(c, "last_network_keys", None))

    def update_task_status(self, node_id, session_id, updates):
        return self._call("update_task_status", node_id, session_id,
                          updates)

    def open_assignments(self, node_id, session_id):
        return self._call("open_assignments", node_id, session_id)

    def publish_logs(self, node_id, session_id, messages):
        return self._call("publish_logs", node_id, session_id, messages)

    def update_volume_status(self, node_id, session_id, updates):
        return self._call("update_volume_status", node_id, session_id,
                          updates)

    @property
    def last_ca_digest(self) -> str:
        """Active root digest from the latest heartbeat (drives prompt
        renewal when a CA rotation begins)."""
        with self._mu:
            return getattr(self._client, "last_ca_digest", "") or ""

    @property
    def last_role(self):
        """This node's store-reconciled role from the latest heartbeat
        (int NodeRole value), or None before the first heartbeat."""
        with self._mu:
            return getattr(self._client, "last_role", None)

    def reset_connection(self) -> None:
        """Drop the live connection so the next call re-handshakes with
        the (possibly renewed) certificate."""
        with self._mu:
            if self._client is not None:
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None
                self._current = None
