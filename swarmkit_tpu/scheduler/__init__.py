from .constraint import Constraint, InvalidConstraint, node_matches, parse
from .filters import Pipeline
from .nodeinfo import MAX_FAILURES, MONITOR_FAILURES, NodeInfo
from .nodeset import NodeSet
from .scheduler import Scheduler
from .volumes import VolumeSet
