"""Placement-constraint expression engine.

Reference: manager/constraint/constraint.go.

Grammar: ``key == value`` / ``key != value`` with case-insensitive full-string
match.  Keys: node.id, node.hostname, node.ip (exact IP or CIDR), node.role,
node.platform.os, node.platform.arch, node.labels.*, engine.labels.*.

The TPU path compiles parsed constraints to hashed (key-id, op, value-hash)
triples evaluated as masks on device (see ops/constraints.py); this module is
the parsing + host-evaluation oracle.
"""

from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass
from typing import List, Sequence

from ..models.objects import Node
from ..models.types import NodeRole

EQ = 0
NOTEQ = 1

NODE_LABEL_PREFIX = "node.labels."
ENGINE_LABEL_PREFIX = "engine.labels."

_KEY_RE = re.compile(r"^[a-z_][a-z0-9\-_.]+$", re.IGNORECASE)
_VALUE_RE = re.compile(
    r"^[a-z0-9:\-_\s.*()?+\[\]\\^$|/]+$", re.IGNORECASE)
_OPERATORS = ("==", "!=")


class InvalidConstraint(ValueError):
    pass


@dataclass(frozen=True)
class Constraint:
    key: str
    operator: int  # EQ | NOTEQ
    exp: str

    def match(self, *values: str) -> bool:
        matched = any(self.exp.lower() == v.lower() for v in values)
        return matched if self.operator == EQ else not matched


def parse(exprs: Sequence[str]) -> List[Constraint]:
    out: List[Constraint] = []
    for e in exprs:
        found = False
        for op_index, op in enumerate(_OPERATORS):
            if op not in e:
                continue
            key, _, value = e.partition(op)
            key = key.strip()
            value = value.strip()
            if not _KEY_RE.match(key):
                raise InvalidConstraint(f"key {key!r} is invalid")
            if not _VALUE_RE.match(value):
                raise InvalidConstraint(f"value {value!r} is invalid")
            out.append(Constraint(key, op_index, value))
            found = True
            break
        if not found:
            raise InvalidConstraint(
                f"constraint expected one operator from {', '.join(_OPERATORS)}")
    return out


def node_matches(constraints: Sequence[Constraint], n: Node) -> bool:
    """reference: manager/constraint/constraint.go:107 NodeMatches."""
    for c in constraints:
        key = c.key.lower()
        if key == "node.id":
            if not c.match(n.id):
                return False
        elif key == "node.hostname":
            hostname = n.description.hostname if n.description else ""
            if not c.match(hostname):
                return False
        elif key == "node.ip":
            if not _match_ip(c, n.status.addr):
                return False
        elif key == "node.role":
            role = "MANAGER" if n.spec.desired_role == NodeRole.MANAGER else "WORKER"
            if not c.match(role):
                return False
        elif key == "node.platform.os":
            os_name = (n.description.platform.os
                       if n.description and n.description.platform else "")
            if not c.match(os_name):
                return False
        elif key == "node.platform.arch":
            arch = (n.description.platform.architecture
                    if n.description and n.description.platform else "")
            if not c.match(arch):
                return False
        elif key.startswith(NODE_LABEL_PREFIX):
            label = c.key[len(NODE_LABEL_PREFIX):]
            val = n.spec.annotations.labels.get(label, "")
            if not c.match(val):
                return False
        elif key.startswith(ENGINE_LABEL_PREFIX):
            label = c.key[len(ENGINE_LABEL_PREFIX):]
            val = (n.description.engine.labels.get(label, "")
                   if n.description and n.description.engine else "")
            if not c.match(val):
                return False
        else:
            # unknown constraint key never matches (reference behavior:
            # constraint.go:188-191 returns false)
            return False
    return True


def ip_column_spec(c: Constraint):
    """Device-path compilation of a node.ip constraint: returns
    (column_key, expected_value) such that hashing each node's
    ``ip_node_value(addr, column_key)`` and comparing against
    ``hash(expected_value)`` under the constraint's ==/!= operator
    reproduces ``_match_ip`` exactly — exact IPs compare canonical
    address strings, CIDRs compare the canonical CONTAINING NETWORK at
    the expression's prefix length (the "hash/prefix column").
    Returns None for a malformed expression: the host rejects every
    node regardless of operator, which the caller encodes as an
    op-==-against-sentinel row."""
    try:
        want = ipaddress.ip_address(c.exp)
        return "node.ip", str(want)
    except ValueError:
        pass
    try:
        subnet = ipaddress.ip_network(c.exp, strict=False)
        return f"node.ip/{subnet.prefixlen}", str(subnet)
    except ValueError:
        return None


def ip_node_value(addr: str, column_key: str) -> str:
    """A node's match value for one node.ip column key: the canonical
    address ("node.ip") or the canonical network containing the
    address at the key's prefix length ("node.ip/<p>").  Unparsable or
    empty addresses yield "" — never equal to a real canonical form,
    matching the host's node_ip-is-None behavior (== rejects,
    != accepts)."""
    try:
        ip = ipaddress.ip_address(addr) if addr else None
    except ValueError:
        ip = None
    if ip is None:
        return ""
    if column_key == "node.ip":
        return str(ip)
    try:
        prefix = int(column_key.rsplit("/", 1)[1])
        return str(ipaddress.ip_network(f"{ip}/{prefix}", strict=False))
    except ValueError:
        return ""


def _match_ip(c: Constraint, addr: str) -> bool:
    try:
        node_ip = ipaddress.ip_address(addr) if addr else None
    except ValueError:
        node_ip = None
    # exact IP
    try:
        want = ipaddress.ip_address(c.exp)
        ip_eq = node_ip is not None and want == node_ip
        return ip_eq if c.operator == EQ else not ip_eq
    except ValueError:
        pass
    # CIDR subnet
    try:
        subnet = ipaddress.ip_network(c.exp, strict=False)
        within = node_ip is not None and node_ip in subnet
        return within if c.operator == EQ else not within
    except ValueError:
        pass
    # malformed expression rejects the node
    return False
