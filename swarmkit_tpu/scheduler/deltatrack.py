"""Dirty-set tracking for the streaming scheduler (ISSUE 14).

The planner's device inputs are densified from the scheduler's NodeSet
mirror.  Rebuilding them every tick costs O(cluster) in Python loops —
fine for one giant cold tick, wrong for steady-state churn where each
tick touches a handful of nodes.  ``DeltaTracker`` folds every mirror
mutation (node create/update/remove, task commit/exit/failure — the
store watch deltas the scheduler's event loop already consumes) into a
per-node dirty set, so ``ops/streaming.ResidentState`` can refresh the
resident columns in O(churn) instead of O(cluster).

Wiring (no new watch plane — the deltas ride the scheduler's existing
block-aware subscription):

* ``NodeSet.tracker`` holds the scheduler's tracker; every NodeInfo
  added through ``add_or_update_node`` gets its ``on_dirty`` hook bound
  to ``tracker.mark``, so ``add_task``/``remove_task``/``task_failed``
  — the only mutation paths for counts, reservations and failures —
  mark the node without the scheduler enumerating call sites.
* Structural changes take the conservative route: node REMOVALS (and
  store resyncs) demand a full rebuild, because the full-rebuild row
  order is the NodeSet dict's insertion order and a removal shifts
  every later row (row index is a placement tie-break key — it must
  never drift between the incremental and full paths).  Node ADDS are
  appended in arrival order, which matches the dict's append order
  exactly, so they stay incremental.

The tracker is deliberately dumb: it records *which* rows changed,
never *what* changed — the resident state recomputes marked rows from
the NodeInfo ground truth, so a redundant mark costs one row recompute
and a missed mark is the only correctness hazard (guarded by the sim's
``incremental-equals-full-replan`` twin-store differential).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: adds beyond this collapse to a full rebuild — the tracker must stay
#: O(churn), and a churn window that replaced half the cluster is not
#: a churn window
MAX_TRACKED_ADDS = 4096


class DeltaTracker:
    __slots__ = ("dirty", "added", "full_reason", "version")

    def __init__(self) -> None:
        self.dirty: Dict[str, None] = {}
        self.added: List[str] = []
        #: pending full-rebuild demand ("cold" until the first drain)
        self.full_reason: Optional[str] = "cold"
        #: bumped on every mutation — device-residency freshness token
        self.version = 0

    # ------------------------------------------------------------- marking

    def mark(self, node_id: str) -> None:
        """A node's resident row went stale (counts, reservations,
        readiness, labels — any of it).  Insertion-ordered and
        deduplicated, so drain order is deterministic."""
        self.dirty[node_id] = None
        self.version += 1

    def note_add(self, node_id: str) -> None:
        """A node joined the mirror (appended to the NodeSet dict)."""
        if self.full_reason is not None:
            return
        if len(self.added) >= MAX_TRACKED_ADDS:
            self.require_full("add-overflow")
            return
        self.added.append(node_id)
        self.version += 1

    def note_remove(self, node_id: str) -> None:
        """A node left the mirror: later rows shift, so the next
        refresh must rebuild (row order is a placement tie-break)."""
        self.require_full("node-remove")

    def require_full(self, reason: str) -> None:
        """Demand a full rebuild at the next refresh.  The first reason
        wins (it is the root cause; later ones are consequences)."""
        if self.full_reason is None:
            self.full_reason = reason
        self.version += 1

    # ------------------------------------------------------------ draining

    def drain(self) -> Tuple[Dict[str, None], List[str], Optional[str]]:
        """Take (dirty ids, added ids, full-rebuild reason) and reset.
        ``dirty`` iterates in mark order, ``added`` in arrival order —
        both deterministic under the sim's seeded event loop."""
        out = (self.dirty, self.added, self.full_reason)
        self.dirty = {}
        self.added = []
        self.full_reason = None
        return out

    @property
    def pending(self) -> bool:
        return bool(self.dirty or self.added
                    or self.full_reason is not None)
