"""Filter pipeline: the per-task node-feasibility checklist.

Reference: manager/scheduler/filter.go (8 filters), pipeline.go (ordered
short-circuit checklist with failure counting for Explain).

This host path is the oracle; the TPU path (ops/) evaluates the same
predicates as vectorized masks over all nodes at once, behind the same
Pipeline seam.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..models.objects import Task
from ..models.types import (
    MountType, NodeAvailability, NodeState, Platform, PublishMode,
)
from . import constraint as constraint_mod
from . import genericresource
from .nodeinfo import NodeInfo
from .volumes import VolumeSet, GROUP_PREFIX


class Filter:
    """reference: filter.go:14"""

    def set_task(self, t: Task) -> bool:
        """Enable the filter for this task; False = not applicable."""
        raise NotImplementedError

    def check(self, n: NodeInfo) -> bool:
        raise NotImplementedError

    def explain(self, nodes: int) -> str:
        raise NotImplementedError


class ReadyFilter(Filter):
    def set_task(self, t: Task) -> bool:
        return True

    def check(self, n: NodeInfo) -> bool:
        return (n.node.status.state == NodeState.READY
                and n.node.spec.availability == NodeAvailability.ACTIVE)

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "1 node not available for new tasks"
        return f"{nodes} nodes not available for new tasks"


class ResourceFilter(Filter):
    def __init__(self) -> None:
        self._reservations = None

    def set_task(self, t: Task) -> bool:
        r = t.spec.resources
        if r is None or r.reservations is None:
            return False
        res = r.reservations
        if not res.nano_cpus and not res.memory_bytes and not res.generic:
            return False
        self._reservations = res
        return True

    def check(self, n: NodeInfo) -> bool:
        res = self._reservations
        if res.nano_cpus > n.available_resources.nano_cpus:
            return False
        if res.memory_bytes > n.available_resources.memory_bytes:
            return False
        for g in res.generic:
            if not genericresource.has_enough(n.available_resources.generic, g):
                return False
        return True

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "insufficient resources on 1 node"
        return f"insufficient resources on {nodes} nodes"


def _references_volume_plugin(mount) -> bool:
    return (mount.type == MountType.VOLUME
            and mount.volume_driver not in ("", "local"))


class PluginFilter(Filter):
    def __init__(self) -> None:
        self._task: Optional[Task] = None

    def set_task(self, t: Task) -> bool:
        c = t.spec.container
        volume_templates = bool(c) and any(
            _references_volume_plugin(m) for m in c.mounts)
        if volume_templates or t.networks or t.spec.log_driver is not None:
            self._task = t
            return True
        return False

    def check(self, n: NodeInfo) -> bool:
        desc = n.node.description
        if desc is None or desc.engine is None:
            # node not running an engine: plugins not supported -> pass
            return True
        plugins = desc.engine.plugins
        t = self._task
        c = t.spec.container
        if c:
            for mount in c.mounts:
                if _references_volume_plugin(mount):
                    _, exists = self._plugin_on_node(
                        "Volume", mount.volume_driver, plugins)
                    if not exists:
                        return False
        for attachment in t.networks:
            # network attachments carry a driver via their network id;
            # resolution happens at allocation time.  A populated driver name
            # is checked against the node's Network plugins.
            driver = getattr(attachment, "driver_name", "")
            if driver:
                _, exists = self._plugin_on_node("Network", driver, plugins)
                if not exists:
                    return False
        log_driver = t.spec.log_driver
        if log_driver is not None and log_driver.name not in ("", "none"):
            type_found, exists = self._plugin_on_node(
                "Log", log_driver.name, plugins)
            if not exists and type_found:
                return False
        return True

    @staticmethod
    def _plugin_on_node(ptype: str, name: str, plugins) -> tuple:
        type_found = False
        for p in plugins:
            if p.type != ptype:
                continue
            type_found = True
            if p.name == name or p.name == name + ":latest":
                return True, True
        return type_found, False

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "missing plugin on 1 node"
        return f"missing plugin on {nodes} nodes"


class ConstraintFilter(Filter):
    def __init__(self) -> None:
        self._constraints: List[constraint_mod.Constraint] = []

    def set_task(self, t: Task) -> bool:
        if not t.spec.placement or not t.spec.placement.constraints:
            return False
        try:
            self._constraints = constraint_mod.parse(
                t.spec.placement.constraints)
        except constraint_mod.InvalidConstraint:
            # validated at the control API; treat bad input as disabled
            return False
        return True

    def check(self, n: NodeInfo) -> bool:
        return constraint_mod.node_matches(self._constraints, n.node)

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "scheduling constraints not satisfied on 1 node"
        return f"scheduling constraints not satisfied on {nodes} nodes"


def normalize_arch(arch: str) -> str:
    if arch == "x86_64":
        return "amd64"
    if arch == "aarch64":
        return "arm64"
    return arch


def platform_equal(img: Platform, node: Platform) -> bool:
    img_arch = normalize_arch(img.architecture)
    node_arch = normalize_arch(node.architecture)
    return ((not img_arch or img_arch == node_arch)
            and (not img.os or img.os == node.os))


class PlatformFilter(Filter):
    def __init__(self) -> None:
        self._platforms: Sequence[Platform] = ()

    def set_task(self, t: Task) -> bool:
        placement = t.spec.placement
        if placement and placement.platforms:
            self._platforms = placement.platforms
            return True
        return False

    def check(self, n: NodeInfo) -> bool:
        if not self._platforms:
            return True
        desc = n.node.description
        if desc and desc.platform:
            return any(platform_equal(p, desc.platform)
                       for p in self._platforms)
        return False

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "unsupported platform on 1 node"
        return f"unsupported platform on {nodes} nodes"


class HostPortFilter(Filter):
    def __init__(self) -> None:
        self._task: Optional[Task] = None

    def set_task(self, t: Task) -> bool:
        if t.endpoint:
            for port in t.endpoint.ports:
                if port.publish_mode == PublishMode.HOST and port.published_port:
                    self._task = t
                    return True
        return False

    def check(self, n: NodeInfo) -> bool:
        for port in self._task.endpoint.ports:
            if port.publish_mode == PublishMode.HOST and port.published_port:
                if (port.protocol, port.published_port) in n.used_host_ports:
                    return False
        return True

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "host-mode port already in use on 1 node"
        return f"host-mode port already in use on {nodes} nodes"


class MaxReplicasFilter(Filter):
    def __init__(self) -> None:
        self._task: Optional[Task] = None

    def set_task(self, t: Task) -> bool:
        if t.spec.placement and t.spec.placement.max_replicas > 0:
            self._task = t
            return True
        return False

    def check(self, n: NodeInfo) -> bool:
        count = n.active_tasks_count_by_service.get(
            self._task.service_id, 0)
        return count < self._task.spec.placement.max_replicas

    def explain(self, nodes: int) -> str:
        return "max replicas per node limit exceed"


class VolumesFilter(Filter):
    def __init__(self, vs: Optional[VolumeSet]) -> None:
        self.vs = vs
        self._task: Optional[Task] = None
        self._requested = []

    def set_task(self, t: Task) -> bool:
        if self.vs is None:
            return False
        self._task = t
        self._requested = []
        c = t.spec.container
        if c is None:
            return False
        for mount in c.mounts:
            if mount.type == MountType.CSI:
                self._requested.append(mount)
        return bool(self._requested)

    def check(self, n: NodeInfo) -> bool:
        for mount in self._requested:
            if not self.vs.is_volume_available_on_node(mount, n):
                return False
        return True

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "cannot fulfill requested volumes on 1 node"
        return f"cannot fulfill requested volumes on {nodes} nodes"


class _Entry:
    __slots__ = ("f", "enabled", "failure_count")

    def __init__(self, f: Filter):
        self.f = f
        self.enabled = False
        self.failure_count = 0


class Pipeline:
    """Ordered short-circuit checklist (reference: pipeline.go:38)."""

    def __init__(self) -> None:
        self._checklist: List[_Entry] = [
            _Entry(ReadyFilter()),
            _Entry(ResourceFilter()),
            _Entry(PluginFilter()),
            _Entry(ConstraintFilter()),
            _Entry(PlatformFilter()),
            _Entry(HostPortFilter()),
            _Entry(MaxReplicasFilter()),
        ]

    def add_filter(self, f: Filter) -> None:
        self._checklist.append(_Entry(f))

    def set_task(self, t: Task) -> None:
        for entry in self._checklist:
            entry.enabled = entry.f.set_task(t)
            entry.failure_count = 0

    def process(self, n: NodeInfo) -> bool:
        for entry in self._checklist:
            if entry.enabled and not entry.f.check(n):
                entry.failure_count += 1
                return False
        for entry in self._checklist:
            entry.failure_count = 0
        return True

    def explain(self) -> str:
        parts = []
        for entry in sorted(self._checklist, key=lambda e: -e.failure_count):
            if entry.failure_count > 0:
                parts.append(entry.f.explain(entry.failure_count))
        return "; ".join(parts)
