"""Gang scheduling: all-or-nothing placement units + the pipeline gate.

A task is a *gang member* when its spec opts in
(``Placement.gang`` — models/types.py).  Members sharing a gang unit
key (``TaskSpec.gang_id``, defaulting to the service id, so one gang
can span services) place **atomically**: either every pending member
of the unit commits in a single epoch-pinned store transaction, or
none does and the whole unit defers to the next tick.  A commit
containing a strict subset of a gang is a bug — the sim's
``gang-atomicity`` invariant (sim/invariants.py) fails the run on one.

The admission flow per unit (``admit_gangs``, driven from the tick):

1. **Pipeline gate** — a unit whose service declares ``depends_on``
   only schedules once the PipelineSupervisor released its stage.
2. **Completeness** — fewer pending members than the largest
   ``min_size`` across the unit defers it (members are still
   materializing in the orchestrator).
3. **Quota, all-or-nothing** — every member group must be admitted in
   full by the TenantLedger; any shortfall rolls back the charges
   already taken (``TenantLedger.uncharge``) and defers the unit.
4. **Device precheck** — ``planner.gang_feasible`` (ops/planner.py)
   runs the ``kernel.gang_fit`` reduction behind the planner breaker;
   the numpy ``gang_fit_host`` oracle below is bit-equal on the same
   densified inputs (the PR 14/15 oracle/kernel discipline), so a
   breaker demotion never changes an admission verdict.
5. **Scratch placement + single-tx commit** — members place through
   the ordinary host group path into a scratch decision set; a
   shortfall rolls every scratch placement back (mirror, volumes,
   quota).  A full placement commits all members in ONE store
   transaction with per-row re-validation — any row changed under us
   aborts the transaction and the unit defers.

Two half-placeable gangs cannot livelock: units admit in a
deterministic (-priority, first-pending age, key) order, so one gang
always wins the capacity race and the other defers intact.

Starvation (satellite of ROADMAP item 7): the preemption pass used to
trigger only for priority > 0 pending work.  Gang units that were
deferred for capacity (``GangState.blocked``) or that have waited
longer than ``SWARM_PREEMPT_AGE`` seconds are *entitled* too
(``preempt_entitled``) — they may evict strictly-lower-priority
victims (evict-only: the gang still places atomically on a later
tick, never one preemptor at a time).

``ATOMIC_ENFORCED`` / ``GATE_ENFORCED`` are checker-sensitivity
seams: tests flip them off to prove the sim's ``gang-atomicity`` and
``pipeline-order`` invariants actually fire (never touch them in
production code).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.objects import Service, Task, Volume
from ..models.types import TaskState, VolumePublishStatus, now
from ..utils.metrics import registry as _metrics
from .preempt import task_priority
from .quota import task_tenant
from .nodeinfo import task_reservations

log = logging.getLogger("gang")

#: checker-sensitivity seams (see module docstring) — tests only
ATOMIC_ENFORCED = True
GATE_ENFORCED = True

#: kernel group-size clamp (ops/kernel.py contract) — duplicated here
#: so the host oracle does not import the jax-heavy ops package
K_CLAMP = 1 << 22

#: age (seconds) after which a still-pending gang unit becomes
#: preemption-entitled even without a recorded capacity deferral
DEFAULT_PREEMPT_AGE = 30.0


def _preempt_age() -> float:
    raw = os.environ.get("SWARM_PREEMPT_AGE", "").strip()
    try:
        return float(raw) if raw else DEFAULT_PREEMPT_AGE
    except ValueError:
        return DEFAULT_PREEMPT_AGE


def gang_cfg(t: Task):
    """The task's GangConfig, or None for ordinary tasks."""
    p = t.spec.placement if t.spec is not None else None
    return p.gang if p is not None else None


def is_gang(t: Task) -> bool:
    return gang_cfg(t) is not None


def gang_unit(t: Task) -> str:
    """Gang unit key: explicit ``gang_id`` or the owning service —
    a shared gang_id joins several services into one atomic unit."""
    gid = getattr(t.spec, "gang_id", "") if t.spec is not None else ""
    return gid or t.service_id


class GangState:
    """Per-scheduler gang bookkeeping (leader-local; rebuilt from the
    pending queue after failover — ages restart, verdicts do not)."""

    def __init__(self) -> None:
        #: unit key -> first time this unit was seen pending-deferred
        self.first_pending: Dict[str, float] = {}
        #: unit keys deferred for capacity/quota (preemption-entitled)
        self.blocked: set = set()
        self.stats = {"gangs_admitted": 0, "gangs_deferred": 0,
                      "gang_tasks_placed": 0, "rollbacks": 0}

    def prune(self, live_keys) -> None:
        """Drop bookkeeping for units no longer pending (placed,
        deleted, or drained) so stale entries cannot keep the
        preemption trigger hot forever."""
        self.blocked &= set(live_keys)
        for key in list(self.first_pending):
            if key not in live_keys:
                del self.first_pending[key]


# --------------------------------------------------------- host oracle


def gang_fit_host(nodes_in, group_in) -> Tuple[bool, np.ndarray]:
    """Numpy replica of ``kernel.gang_fit`` (ops/kernel.py) on the SAME
    densified inputs: (fit, fail_counts i32[8]).

    Bit-equality argument: the masks and the capacity formula are
    integer/boolean, identical term for term; the only float is the
    final f32 capacity sum, whose >= k comparison is decided
    identically despite summation-order differences — totals < 2^24
    are exact in f32 (all addends non-negative), and totals >= 2^24
    are far above k <= K_CLAMP = 2^22 under any rounding."""
    valid = np.asarray(nodes_in.valid, bool)
    ready_m = np.asarray(nodes_in.ready, bool)
    res_m = np.asarray(nodes_in.res_ok, bool)
    plugin_m = np.asarray(nodes_in.extra_mask, bool)

    con_hash = np.asarray(group_in.con_hash)
    con_op = np.asarray(group_in.con_op)
    con_exp = np.asarray(group_in.con_exp)
    con_m = np.ones_like(ready_m)
    for i in range(con_op.shape[0]):
        eq = ((con_hash[i, 0] == con_exp[i, 0])
              & (con_hash[i, 1] == con_exp[i, 1]))
        op = int(con_op[i])
        if op == 0:
            con_m &= eq
        elif op == 1:
            con_m &= ~eq

    plat = np.asarray(group_in.plat)
    os_hash = np.asarray(nodes_in.os_hash)
    arch_hash = np.asarray(nodes_in.arch_hash)
    matched = np.zeros_like(ready_m)
    any_used = False
    for i in range(plat.shape[0]):
        row = plat[i]
        if row[0] == -1:
            continue
        any_used = True
        os_ok = ((row[0] == 0) & (row[1] == 0)) | (
            (os_hash[0] == row[0]) & (os_hash[1] == row[1]))
        arch_ok = ((row[2] == 0) & (row[3] == 0)) | (
            (arch_hash[0] == row[2]) & (arch_hash[1] == row[3]))
        matched |= os_ok & arch_ok
    plat_m = matched if any_used else np.ones_like(ready_m)

    port_limited = bool(group_in.port_limited)
    port_m = ~(port_limited & np.asarray(nodes_in.port_conflict, bool))
    maxrep = int(group_in.maxrep)
    svc_tasks = np.asarray(nodes_in.svc_tasks)
    rep_m = np.ones_like(ready_m) if maxrep == 0 else svc_tasks < maxrep
    quota_m = (np.asarray(nodes_in.quota_ok, bool)
               if nodes_in.quota_ok is not None
               else np.ones_like(ready_m))

    fail_counts = np.zeros(8, np.int32)
    mask = valid
    for fi, m in enumerate((ready_m, res_m, plugin_m, con_m, plat_m,
                            port_m, rep_m, quota_m)):
        fails = mask & ~m
        fail_counts[fi] = int(np.sum(fails))
        mask = mask & m

    k = min(int(group_in.k), K_CLAMP)
    cap = np.minimum(np.asarray(nodes_in.res_cap, np.int32),
                     np.int32(k))
    if maxrep > 0:
        cap = np.minimum(cap, np.maximum(
            np.int32(maxrep) - svc_tasks, 0).astype(np.int32))
    if port_limited:
        cap = np.minimum(cap, 1)
    cap = np.where(mask, np.maximum(cap, 0), 0).astype(np.int32)
    total = np.sum(cap.astype(np.float32))
    return bool(total >= np.float32(k)), fail_counts


# ----------------------------------------------------- queue extraction


def take_gangs(groups: Dict, one_off_tasks: Dict
               ) -> "List[Tuple[str, List[Dict[str, Task]]]]":
    """Pull every gang member out of the tick's taken queue (service
    groups AND the one-off bucket) and fold them into units.  Pure
    no-op when no task opts in — non-gang ticks stay byte-identical.
    Returns [(unit key, [member group dict, ...])] with deterministic
    member-group order (queue insertion order, one-offs last)."""
    units: Dict[str, List[Dict[str, Task]]] = {}
    for key in list(groups):
        group = groups[key]
        t0 = next((t for t in group.values() if t is not None), None)
        if t0 is None or not is_gang(t0):
            continue
        members = {tid: t for tid, t in group.items()
                   if t is not None and not t.node_id}
        del groups[key]
        if members:
            units.setdefault(gang_unit(t0), []).append(members)
    gone: List[str] = []
    for tid, t in one_off_tasks.items():
        if t is None or t.node_id or not is_gang(t):
            continue
        units.setdefault(gang_unit(t), []).append({tid: t})
        gone.append(tid)
    for tid in gone:
        del one_off_tasks[tid]
    return list(units.items())


# ------------------------------------------------------- pipeline gate


def _gate_err(service: Service) -> Optional[str]:
    """Deferral message when ``service``'s pipeline stage is not
    released, or None when the stage may schedule.  Fail-safe: a
    dependent service with no supervisor verdict yet is gated."""
    if not service.spec.depends_on:
        return None
    st = service.pipeline_status
    if st is None:
        return "awaiting upstream pipeline stage"
    if st.state == "released":
        return None
    if st.state == "halted":
        return (f"pipeline halted ({st.reason})" if st.reason
                else "pipeline halted")
    return "awaiting upstream pipeline stage"


def gate_err_for(sched, t: Task) -> Optional[str]:
    """Gate verdict for a task, from the replicated Service row."""
    if not GATE_ENFORCED or not t.service_id:
        return None
    service = sched.store.raw_get(Service, t.service_id)
    if service is None:
        return None
    return _gate_err(service)


def is_gated(sched, t: Task) -> bool:
    return gate_err_for(sched, t) is not None


def pipeline_gate(sched, group: Dict[str, Task],
                  decisions) -> Dict[str, Task]:
    """Tick-side gate for ordinary (non-gang) groups: a group whose
    service awaits an upstream pipeline stage defers wholesale with a
    pipeline message instead of flowing to placement (gang units run
    the same check inside ``admit_gangs``)."""
    t0 = next(iter(group.values()))
    err = gate_err_for(sched, t0)
    if err is None:
        return group
    defer_tasks(sched, list(group.values()), err, decisions)
    return {}


def defer_tasks(sched, tasks: List[Task], err: str, decisions) -> None:
    """The quota-defer discipline (scheduler._quota_defer): stamp the
    reason, re-enqueue for the next tick, and record a decision so the
    status write commits this tick.  Deferred tasks carry no quota
    charge (preemption headroom must not count them)."""
    from .scheduler import SchedulingDecision
    ts = now()
    for t in tasks:
        sched.quota.deferred_tasks.add(t.id)
    for t in tasks:
        new_t = t.copy()
        new_t.status.timestamp = ts
        new_t.status.err = err
        sched.all_tasks[t.id] = new_t
        sched._enqueue(new_t)
        if decisions is not None:
            decisions[t.id] = SchedulingDecision(t, new_t)


# --------------------------------------------------- atomic admission


def _unit_sort_key(sched, key: str, member_groups) -> Tuple:
    """Deterministic admission order — the livelock breaker: priority
    first, then how long the unit has been waiting (older first), then
    the key itself.  Two half-placeable gangs always race in the same
    order, so one places and the other defers intact."""
    prio = max(task_priority(next(iter(g.values())))
               for g in member_groups)
    age = sched.gang.first_pending.get(key, float("inf"))
    return (-prio, age, key)


def _rollback_scratch(sched, scratch) -> None:
    """Undo scratch placements' mirror mutations (the tick's standard
    failed-decision rollback, minus the re-enqueue — deferral stamps
    handle that)."""
    for d in scratch.values():
        sched.all_tasks[d.old.id] = d.old
        info = sched.node_set.node_info(d.new.node_id)
        if info is not None:
            info.remove_task(d.new)
        for va in d.new.volumes:
            sched.volumes.release_volume(va.id, d.new.id)


def _commit_unit(sched, scratch) -> bool:
    """Commit every member's assignment in ONE store transaction,
    re-validating each row in-tx (the _commit_preemption discipline):
    a member that changed under us — assigned elsewhere, shut down,
    version bumped — aborts the whole transaction, so the store never
    observes a partial gang.  Volume publish staging matches
    scheduler._apply_decisions_tx."""
    proposer = sched.store._proposer
    if proposer is not None \
            and getattr(proposer, "leadership_epoch", None) \
            != sched._tick_epoch:
        return False    # the tick's reign is over: nothing may commit
    result: Dict[str, bool] = {}

    def cb(tx) -> None:
        rows = []
        vols: Dict[str, Volume] = {}
        for d in scratch.values():
            cur = tx.get(Task, d.old.id)
            if (cur is None or cur.node_id
                    or cur.status.state != TaskState.PENDING
                    or cur.desired_state > TaskState.COMPLETE
                    or cur.meta.version.index
                    != d.old.meta.version.index):
                return    # write nothing: the unit defers intact
            for va in d.new.volumes:
                v = vols.get(va.id)
                if v is None:
                    v = tx.get(Volume, va.id)
                if v is None or v.spec.availability != 0:
                    return
                if not any(ps.node_id == d.new.node_id
                           for ps in v.publish_status):
                    v = v.copy()
                    v.publish_status.append(VolumePublishStatus(
                        node_id=d.new.node_id,
                        state=VolumePublishStatus.State.PENDING_PUBLISH))
                vols[va.id] = v
            rows.append(d.new)
        for r in rows:
            tx.update(r)
        for v in vols.values():
            tx.update(v)
        result["ok"] = True

    try:
        sched.store.update(cb)
    except Exception:
        log.exception("gang commit transaction failed")
        return False
    return result.get("ok", False)


def admit_gangs(sched, units, decisions) -> int:
    """Admit gang units atomically (see module docstring for the
    five-step flow).  Returns gang tasks placed this tick; deferral
    stamps ride the OUTER ``decisions`` dict (committed with the
    tick's other status writes), placed members commit here in their
    own single transactions and never enter ``decisions``."""
    state: GangState = sched.gang
    ledger = sched.quota
    quota_on = sched.quota_enabled and ledger.active
    planner = sched.batch_planner
    placed_total = 0
    units = sorted(units, key=lambda u: _unit_sort_key(sched, u[0], u[1]))

    for key, member_groups in units:
        members = [t for g in member_groups for t in g.values()]

        def deferred(err: str, blocked: bool) -> None:
            defer_tasks(sched, members, err, decisions)
            state.stats["gangs_deferred"] += 1
            _metrics.counter("swarm_gang_deferred", 1)
            if blocked:
                state.blocked.add(key)
                state.first_pending.setdefault(key, now())

        # 1. pipeline gate (any gated member service gates the unit)
        err = None
        for g in member_groups:
            err = gate_err_for(sched, next(iter(g.values())))
            if err is not None:
                break
        if err is not None:
            deferred(err, blocked=False)
            continue

        # 2. completeness: wait for the orchestrator to materialize
        # the whole gang before attempting placement.  Members already
        # placed and live count toward min_size — a gang that lost one
        # member to node churn only needs its REPLACEMENT pending, not
        # a whole new gang (else churn deadlocks the unit forever).
        need = max((gang_cfg(t).min_size for t in members
                    if gang_cfg(t) is not None), default=0)
        placed_live = sum(
            1 for t in sched.all_tasks.values()
            if t.node_id and is_gang(t) and gang_unit(t) == key
            and t.desired_state <= TaskState.COMPLETE
            and t.status.state <= int(TaskState.RUNNING))
        if len(members) + placed_live < need:
            deferred(f'gang "{key}" incomplete '
                     f'({len(members)}/{max(need - placed_live, 0)} '
                     f'members pending)', blocked=False)
            continue

        # 3. quota: all member groups admit in full or none do
        charges: List[Tuple[str, int, int, int, Task]] = []
        short_tenant: Optional[str] = None
        if quota_on:
            for g in member_groups:
                t0 = next(iter(g.values()))
                tenant = task_tenant(t0)
                res = task_reservations(t0)
                cpu_d = int(res.nano_cpus)
                mem_d = int(res.memory_bytes)
                admit = ledger.admit(tenant, cpu_d, mem_d, len(g))
                if admit is not None and admit < len(g):
                    short_tenant = tenant
                    break
                if admit is not None:
                    ledger.charge(tenant, cpu_d, mem_d, len(g))
                    ledger.note_group_charge(t0, len(g))
                    charges.append((tenant, cpu_d, mem_d, len(g), t0))

        def uncharge_all() -> None:
            for tenant, cpu_d, mem_d, n, t0 in charges:
                ledger.uncharge(tenant, cpu_d, mem_d, n)
                ledger.note_group_charge(t0, -n)

        if short_tenant is not None:
            uncharge_all()
            deferred(f'gang "{key}" over tenant quota '
                     f'(tenant "{short_tenant}")', blocked=True)
            continue

        # 4. device feasibility precheck (breaker-routed; the host
        # oracle serves demotions bit-identically).  None = no verdict
        # (planner absent / bucket overflow): the placement attempt +
        # rollback below decides instead.  The seam disables the whole
        # all-or-nothing apparatus, precheck included, so the partial
        # commit the sensitivity test needs can actually happen.
        feasible: Optional[bool] = None
        if planner is not None and ATOMIC_ENFORCED:
            wants = [(next(iter(g.values())), len(g))
                     for g in member_groups]
            if len(wants) >= 2 \
                    and hasattr(planner, "gang_feasible_many"):
                # multi-service unit: the fused gang route judges all
                # member groups in one device call
                verdicts = planner.gang_feasible_many(sched, wants)
            elif hasattr(planner, "gang_feasible"):
                verdicts = [planner.gang_feasible(sched, tg, k)
                            for tg, k in wants]
            else:
                verdicts = []
            if any(v is False for v in verdicts):
                feasible = False
        if feasible is False:
            uncharge_all()
            deferred(f'gang "{key}" deferred: all-or-nothing '
                     f'placement infeasible', blocked=True)
            continue

        # 5. scratch placement through the ordinary host group path,
        # then the single-transaction commit
        scratch: Dict[str, object] = {}
        leftover: List[Task] = []
        for g in member_groups:
            work = dict(g)
            sched._schedule_group_host(work, scratch,
                                       defer_leftover=False)
            if work:
                leftover.extend(work.values())
        if leftover and ATOMIC_ENFORCED:
            state.stats["rollbacks"] += 1
            _rollback_scratch(sched, scratch)
            uncharge_all()
            deferred(f'gang "{key}" deferred: all-or-nothing '
                     f'placement infeasible', blocked=True)
            continue
        if leftover:
            # seam OFF (tests only): commit the partial subset so the
            # sim's gang-atomicity checker proves it fires
            defer_tasks(sched, leftover,
                        f'gang "{key}" partially placed', decisions)
        if not scratch:
            uncharge_all()
            deferred(f'gang "{key}" deferred: all-or-nothing '
                     f'placement infeasible', blocked=True)
            continue
        if not _commit_unit(sched, scratch):
            state.stats["rollbacks"] += 1
            _rollback_scratch(sched, scratch)
            uncharge_all()
            deferred(f'gang "{key}" deferred: atomic commit failed',
                     blocked=False)
            continue

        placed = len(scratch)
        placed_total += placed
        state.stats["gangs_admitted"] += 1
        state.stats["gang_tasks_placed"] += placed
        _metrics.counter("swarm_gang_admitted", 1)
        _metrics.counter("swarm_gang_tasks_placed", placed)
        state.blocked.discard(key)
        state.first_pending.pop(key, None)

    return placed_total


# -------------------------------------------------- preemption triggers


def preempt_entitled(sched, t: Task) -> bool:
    """Whether a priority-0 gang group may enter the preemption pass
    (satellite of ROADMAP item 7): deferred-for-capacity units and
    units pending longer than SWARM_PREEMPT_AGE are entitled to evict
    strictly-lower-priority victims (evict-only — the gang itself
    still places atomically on a later tick)."""
    if not is_gang(t):
        return False
    key = gang_unit(t)
    if key in sched.gang.blocked:
        return True
    first = sched.gang.first_pending.get(key)
    return first is not None and now() - first >= _preempt_age()
