"""Generic (custom) resource accounting: GPUs, FPGAs, licensed slots...

Reference: api/genericresource/ (Claim resource_management.go:11,
Reclaim :75, HasEnough validate.go:24, ConsumeNodeResources helpers.go:58).

Two shapes:
* DISCRETE — a count ("gpu": 4).
* NAMED    — a set of named units ("gpu": {"uuid1", "uuid2"}); claims pick
  specific units so agents can pin them (surfaced as env vars downstream).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ..models.types import GenericResource, GenericResourceKind


def _count(avail: Sequence[GenericResource], kind: str) -> Tuple[int, bool]:
    """Return (available amount, any named units) for a resource kind."""
    total = 0
    named = False
    for r in avail:
        if r.kind != kind:
            continue
        if r.res_type == GenericResourceKind.NAMED:
            total += 1
            named = True
        else:
            total += r.value
    return total, named


def has_enough(node_available: Sequence[GenericResource],
               requested: GenericResource) -> bool:
    want = requested.value if requested.res_type == GenericResourceKind.DISCRETE else 1
    got, _ = _count(node_available, requested.kind)
    return got >= want


def claim(node_available: List[GenericResource],
          task_assigned: List[GenericResource],
          requested: Sequence[GenericResource]) -> None:
    """Move `requested` amounts from node_available into task_assigned.

    Named units are claimed preferentially (so they can be surfaced to the
    task); discrete counts cover the rest.
    """
    for req in requested:
        want = req.value
        # claim named units first
        i = 0
        while want > 0 and i < len(node_available):
            r = node_available[i]
            if r.kind == req.kind and r.res_type == GenericResourceKind.NAMED:
                task_assigned.append(r)
                node_available.pop(i)
                want -= 1
                continue
            i += 1
        # then discrete counts
        if want > 0:
            for i, r in enumerate(node_available):
                if r.kind == req.kind and r.res_type == GenericResourceKind.DISCRETE:
                    take = min(want, r.value)
                    if take > 0:
                        task_assigned.append(GenericResource(
                            kind=req.kind, value=take))
                        remaining = r.value - take
                        if remaining:
                            node_available[i] = GenericResource(
                                kind=r.kind, value=remaining)
                        else:
                            node_available.pop(i)
                        want -= take
                    break


def reclaim(node_available: List[GenericResource],
            task_assigned: Sequence[GenericResource],
            node_declared: Sequence[GenericResource]) -> None:
    """Return a task's assigned resources to the node's available pool."""
    for r in task_assigned:
        if r.res_type == GenericResourceKind.NAMED:
            node_available.append(r)
        else:
            for i, a in enumerate(node_available):
                if a.kind == r.kind and a.res_type == GenericResourceKind.DISCRETE:
                    node_available[i] = GenericResource(
                        kind=a.kind, value=a.value + r.value)
                    break
            else:
                node_available.append(r)


def consume(node_available: List[GenericResource],
            task_assigned: Sequence[GenericResource]) -> None:
    """Subtract a task's assignment from a freshly-copied node resource list
    (reference: ConsumeNodeResources helpers.go:58)."""
    for r in task_assigned:
        if r.res_type == GenericResourceKind.NAMED:
            for i, a in enumerate(node_available):
                if (a.res_type == GenericResourceKind.NAMED
                        and a.kind == r.kind and a.value_str == r.value_str):
                    node_available.pop(i)
                    break
        else:
            for i, a in enumerate(node_available):
                if a.kind == r.kind and a.res_type == GenericResourceKind.DISCRETE:
                    remaining = a.value - r.value
                    if remaining > 0:
                        node_available[i] = GenericResource(
                            kind=a.kind, value=remaining)
                    else:
                        node_available.pop(i)
                    break
