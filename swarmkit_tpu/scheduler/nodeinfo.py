"""Per-node scheduling scratch state.

Reference: manager/scheduler/nodeinfo.go.

One NodeInfo per node, mutated in place.  (The reference nominally copies
NodeInfo values, but every interesting field is a Go map or pointer shared
between copies, so shared mutation is the actual semantics — we make that
explicit.)
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Set, Tuple

from ..models.objects import Node, Task
from ..models.types import (
    PortConfig, PublishMode, Resources, TaskState, now,
)
from . import genericresource

# Failure down-weighting knobs (reference: scheduler.go:16-24)
MONITOR_FAILURES = 5 * 60.0   # seconds
MAX_FAILURES = 5

# (service_id, spec_version_index)
VersionedService = Tuple[str, int]
# (protocol, published_port)
HostPortSpec = Tuple[int, int]


def task_reservations(task: Task) -> Resources:
    r = task.spec.resources
    if r and r.reservations:
        return r.reservations
    return Resources()


def _versioned_service(t: Task) -> VersionedService:
    return (t.service_id, t.spec_version.index if t.spec_version else 0)


class NodeInfo:
    __slots__ = (
        "node", "tasks", "active_tasks_count", "active_tasks_count_by_service",
        "available_resources", "used_host_ports", "recent_failures",
        "last_cleanup", "on_dirty",
    )

    def __init__(self, node: Node, tasks: Optional[Dict[str, Task]] = None,
                 available: Optional[Resources] = None):
        # streaming-scheduler dirty hook (scheduler/deltatrack.py):
        # bound to the tracker's mark() when the NodeSet carries one, so
        # every count/reservation/failure mutation below invalidates the
        # node's resident device-input row without the scheduler having
        # to enumerate call sites
        self.on_dirty = None
        self.node = node
        self.tasks: Dict[str, Task] = {}
        self.active_tasks_count = 0
        self.active_tasks_count_by_service: Dict[str, int] = {}
        self.available_resources: Resources = (
            available.copy() if available else Resources())
        self.used_host_ports: Set[HostPortSpec] = set()
        self.recent_failures: Dict[VersionedService, List[float]] = {}
        self.last_cleanup = now()
        if tasks:
            for t in tasks.values():
                self.add_task(t)

    # convenience pass-throughs
    @property
    def id(self) -> str:
        return self.node.id

    def remove_task(self, t: Task) -> bool:
        old = self.tasks.pop(t.id, None)
        if old is None:
            return False
        if self.on_dirty is not None:
            self.on_dirty(self.node.id)
        if old.desired_state <= TaskState.COMPLETE:
            self.active_tasks_count -= 1
            self.active_tasks_count_by_service[t.service_id] = (
                self.active_tasks_count_by_service.get(t.service_id, 0) - 1)

        if t.endpoint:
            for port in t.endpoint.ports:
                if port.publish_mode == PublishMode.HOST and port.published_port:
                    self.used_host_ports.discard(
                        (port.protocol, port.published_port))

        reservations = task_reservations(t)
        self.available_resources.memory_bytes += reservations.memory_bytes
        self.available_resources.nano_cpus += reservations.nano_cpus

        desc = self.node.description
        if desc and desc.resources and desc.resources.generic:
            genericresource.reclaim(
                self.available_resources.generic,
                t.assigned_generic_resources,
                desc.resources.generic)
        return True

    def add_task(self, t: Task) -> bool:
        old = self.tasks.get(t.id)
        if old is not None:
            if (t.desired_state <= TaskState.COMPLETE
                    and old.desired_state > TaskState.COMPLETE):
                if self.on_dirty is not None:
                    self.on_dirty(self.node.id)
                self.tasks[t.id] = t
                self.active_tasks_count += 1
                self.active_tasks_count_by_service[t.service_id] = (
                    self.active_tasks_count_by_service.get(t.service_id, 0) + 1)
                return True
            if (t.desired_state > TaskState.COMPLETE
                    and old.desired_state <= TaskState.COMPLETE):
                if self.on_dirty is not None:
                    self.on_dirty(self.node.id)
                self.tasks[t.id] = t
                self.active_tasks_count -= 1
                self.active_tasks_count_by_service[t.service_id] = (
                    self.active_tasks_count_by_service.get(t.service_id, 0) - 1)
                return True
            # object refresh with no count/reservation change: the
            # resident row is untouched — do not dirty it (status-only
            # task progressions are the highest-volume event class)
            return False

        if self.on_dirty is not None:
            self.on_dirty(self.node.id)
        self.tasks[t.id] = t
        reservations = task_reservations(t)
        self.available_resources.memory_bytes -= reservations.memory_bytes
        self.available_resources.nano_cpus -= reservations.nano_cpus

        t.assigned_generic_resources = []
        genericresource.claim(self.available_resources.generic,
                              t.assigned_generic_resources,
                              reservations.generic)

        if t.endpoint:
            for port in t.endpoint.ports:
                if port.publish_mode == PublishMode.HOST and port.published_port:
                    self.used_host_ports.add(
                        (port.protocol, port.published_port))

        if t.desired_state <= TaskState.COMPLETE:
            self.active_tasks_count += 1
            self.active_tasks_count_by_service[t.service_id] = (
                self.active_tasks_count_by_service.get(t.service_id, 0) + 1)
        return True

    # ------------------------------------------------- failure down-weighting

    def _cleanup_failures(self, ts: float) -> None:
        for key in list(self.recent_failures):
            if all(ts - stamp >= MONITOR_FAILURES
                   for stamp in self.recent_failures[key]):
                del self.recent_failures[key]
        self.last_cleanup = ts

    def task_failed(self, t: Task) -> None:
        if self.on_dirty is not None:
            self.on_dirty(self.node.id)
        ts = now()
        if ts - self.last_cleanup >= MONITOR_FAILURES:
            self._cleanup_failures(ts)
        key = _versioned_service(t)
        stamps = self.recent_failures.get(key, [])
        expired = 0
        for stamp in stamps:
            if ts - stamp < MONITOR_FAILURES:
                break
            expired += 1
        self.recent_failures[key] = stamps[expired:] + [ts]

    def count_recent_failures(self, ts: float, t: Task) -> int:
        stamps = self.recent_failures.get(_versioned_service(t), [])
        count = len(stamps)
        for i in range(count - 1, -1, -1):
            if ts - stamps[i] > MONITOR_FAILURES:
                count -= i + 1
                break
        return count
