"""Node set + spread-preference decision tree with bounded max-heaps.

Reference: manager/scheduler/nodeset.go, decision_tree.go, nodeheap.go.

The tree partitions nodes by placement-preference label values; each leaf
keeps a max-heap of at most ``max_assignments`` best candidates (never need
more than n nodes to place n tasks — design/scheduler.md:155-161).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from ..models.objects import Node
from ..models.types import PlacementPreference
from .constraint import ENGINE_LABEL_PREFIX, NODE_LABEL_PREFIX
from .nodeinfo import NodeInfo

LessFunc = Callable[[NodeInfo, NodeInfo], bool]
ConstraintFunc = Callable[[NodeInfo], bool]


class _MaxHeap:
    """Bounded max-heap keyed by a less function, worst node at the root
    (reference: nodeheap.go)."""

    __slots__ = ("nodes", "less", "length")

    def __init__(self, less: LessFunc):
        self.nodes: List[NodeInfo] = []
        self.less = less
        self.length = 0

    def _hless(self, i: int, j: int) -> bool:
        # reversed comparator makes it a max-heap
        return self.less(self.nodes[j], self.nodes[i])

    def _swap(self, i: int, j: int) -> None:
        self.nodes[i], self.nodes[j] = self.nodes[j], self.nodes[i]

    def _up(self, j: int) -> None:
        while j > 0:
            i = (j - 1) // 2
            if not self._hless(j, i):
                break
            self._swap(i, j)
            j = i

    def _down(self, i: int, n: int) -> None:
        while True:
            left = 2 * i + 1
            if left >= n:
                break
            j = left
            right = left + 1
            if right < n and self._hless(right, left):
                j = right
            if not self._hless(j, i):
                break
            self._swap(i, j)
            i = j

    def push(self, node: NodeInfo) -> None:
        self.nodes.append(node)
        self.length += 1
        self._up(self.length - 1)

    def fix_root(self) -> None:
        self._down(0, self.length)

    def heapify(self) -> None:
        for i in range(self.length // 2 - 1, -1, -1):
            self._down(i, self.length)

    def collapse_sorted(self) -> List[NodeInfo]:
        """Pop everything in place: best-first order in self.nodes."""
        while self.length > 0:
            self.length -= 1
            self._swap(0, self.length)
            self._down(0, self.length)
        return self.nodes


class DecisionTree:
    __slots__ = ("tasks", "next", "heap")

    def __init__(self) -> None:
        self.tasks = 0
        self.next: Optional[Dict[str, "DecisionTree"]] = None
        self.heap: Optional[_MaxHeap] = None

    def ordered_nodes(self, meets_constraints: ConstraintFunc) -> List[NodeInfo]:
        """Sorted best-first candidate list; on reuse, re-filters mutated
        nodes and re-sorts (reference: decision_tree.go:24)."""
        if self.heap is None:
            return []
        if self.heap.length != len(self.heap.nodes):
            # already collapsed once; nodes may have mutated
            kept = [n for n in self.heap.nodes if meets_constraints(n)]
            self.heap.nodes = kept
            self.heap.length = len(kept)
            self.heap.heapify()
        return self.heap.collapse_sorted()


def _pref_value(node: NodeInfo, descriptor: str) -> Optional[str]:
    d = descriptor.lower()
    if len(descriptor) > len(NODE_LABEL_PREFIX) and \
            d.startswith(NODE_LABEL_PREFIX):
        return node.node.spec.annotations.labels.get(
            descriptor[len(NODE_LABEL_PREFIX):], "")
    if len(descriptor) > len(ENGINE_LABEL_PREFIX) and \
            d.startswith(ENGINE_LABEL_PREFIX):
        desc = node.node.description
        if desc and desc.engine:
            return desc.engine.labels.get(
                descriptor[len(ENGINE_LABEL_PREFIX):], "")
        return ""
    return None  # unsupported descriptor: skip this preference level


class NodeSet:
    """reference: nodeset.go:14"""

    def __init__(self) -> None:
        self.nodes: Dict[str, NodeInfo] = {}
        # streaming-scheduler delta feed (scheduler/deltatrack.py):
        # membership changes and per-node mutations (via the NodeInfo
        # on_dirty hook bound below) fold into the tracker's dirty set
        self.tracker = None

    def node_info(self, node_id: str) -> Optional[NodeInfo]:
        return self.nodes.get(node_id)

    def add_or_update_node(self, n: NodeInfo) -> None:
        tracker = self.tracker
        if tracker is not None:
            n.on_dirty = tracker.mark
            if n.id in self.nodes:
                # existing-id replacement: the resident row mirrors the
                # OLD NodeInfo object — mark so it re-reads this one
                tracker.mark(n.id)
            else:
                tracker.note_add(n.id)
        self.nodes[n.id] = n

    def remove(self, node_id: str) -> None:
        if self.tracker is not None and node_id in self.nodes:
            self.tracker.note_remove(node_id)
        self.nodes.pop(node_id, None)

    def tree(self, service_id: str,
             preferences: Sequence[PlacementPreference],
             max_assignments: int,
             meets_constraints: ConstraintFunc,
             node_less: LessFunc) -> DecisionTree:
        root = DecisionTree()
        if max_assignments == 0:
            return root

        for node in self.nodes.values():
            tree = root
            for pref in preferences:
                if pref.spread is None:
                    continue
                value = _pref_value(node, pref.spread.spread_descriptor)
                if value is None:
                    continue
                tree.tasks += node.active_tasks_count_by_service.get(
                    service_id, 0)
                if tree.next is None:
                    tree.next = {}
                nxt = tree.next.get(value)
                if nxt is None:
                    nxt = DecisionTree()
                    tree.next[value] = nxt
                tree = nxt

            tree.tasks += node.active_tasks_count_by_service.get(service_id, 0)
            if tree.heap is None:
                tree.heap = _MaxHeap(node_less)

            if tree.heap.length < max_assignments:
                if meets_constraints(node):
                    tree.heap.push(node)
            elif node_less(node, tree.heap.nodes[0]):
                if meets_constraints(node):
                    tree.heap.nodes[0] = node
                    tree.heap.fix_root()
        return root
