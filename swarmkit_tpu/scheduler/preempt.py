"""Priority preemption: host oracle + supervisor state.

When a higher-priority task group comes back infeasible from the normal
scheduling pass, the scheduler may evict ("preempt") strictly-lower-
priority running tasks to make room.  This module is the HOST side of
that capability:

* ``build_candidates`` densifies the scheduler's NodeSet mirror into the
  victims×nodes candidate arrays BOTH selection paths consume — the
  single source that makes the device kernel (ops/preempt.py) byte-
  identical to the host oracle by construction (the same discipline as
  the planner's host-side ``res_ok`` columns).
* ``select_victims_host`` is the oracle: a deterministic greedy that,
  per pending task, picks the node whose cheapest victim prefix frees
  enough resources — cost = Σ(victim priority + 1), ties broken by
  victim count then node index.  The device kernel computes exactly the
  same integers (differential-fuzzed in tests/test_preemption.py).
* ``PreemptSupervisor`` owns the policy state: the per-tick victim
  budget, the per-slot anti-thrash cooldown (stamped via
  ``models.types.now()`` so the sim drives it under virtual time), the
  victim-exit latency stamps, and the counters/gauges the obs plane
  reads (``swarm_preemptions{reason=}``, ``swarm_priority_inversion``).

Selection model (shared spec, mirrored bit-for-bit by the kernel):

  Per node j, candidate victims are pre-sorted (priority asc, task id
  asc) and truncated to the V bucket.  A pick needs the smallest prefix
  m such that ``free[j] + extra[j] + Σ freed[s<m, unused] >= demand``
  for BOTH cpu and memory; its cost is the prefix's unused weight sum.
  Picks run sequentially: the chosen node's prefix is marked used and
  its surplus (freed − demand) carries into ``extra`` for later picks;
  a pick whose victim count exceeds the remaining budget STOPS the
  selection (and everything after it), as does the first infeasible
  pick — all integer math, so host and device agree exactly.

Scope (documented waivers, mirroring the device planner's): preemption
only triggers for priority > 0 pending work whose infeasibility is
resource-shaped — cpu/memory reservations plus AT MOST ONE discrete
generic-resource kind (victims free all three; the selection carries a
third resource column through host and device alike).  Groups demanding
multiple generic kinds, NAMED generics, host ports, or CSI volumes are
still skipped (``swarm_preempt_skipped{reason="unsupported"}`` — the
waiver, narrowed from "any generic" by ISSUE 12).  Victims are always
STRICTLY lower priority; equal-or-higher is excluded at candidate-build
time and re-asserted by the sim's ``no-preempt-equal-or-higher``
invariant.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.objects import Task
from ..models.types import (
    GenericResourceKind, MountType, NodeAvailability, NodeState,
    PublishMode, TaskState, now,
)
from ..utils.metrics import registry as _metrics
from .filters import Pipeline, ResourceFilter
from .nodeinfo import NodeInfo, task_reservations

log = logging.getLogger("preempt")

#: victims considered per node, smallest bucket that fits (shape ladder
#: shared with ops/preempt.py — one jit signature per bucket)
V_BUCKETS = (4, 16, 64)

#: victim weight clamp: cost sums must fit the device kernel's packed
#: (cost, nvict, node) tie-break key (64 victims x 2^20 < 2^27)
PRIO_CLAMP = (1 << 20) - 1

#: default per-tick victim budget (SWARM_PREEMPT_BUDGET): bounds how
#: much running work one tick may evict, so a priority storm degrades
#: gradually instead of mass-evicting the cluster
DEFAULT_BUDGET = 32

#: default per-slot anti-thrash cooldown in seconds
#: (SWARM_PREEMPT_COOLDOWN): a slot preempted once is exempt until the
#: cooldown elapses, so a victim's requeued replacement cannot be
#: evicted again immediately
DEFAULT_COOLDOWN = 60.0

# cached Timer references (Registry.reset() resets in place)
_COMMIT_TIMER = _metrics.timer('swarm_preempt_latency{edge="commit"}')
_EXIT_TIMER = _metrics.timer('swarm_preempt_latency{edge="victim_exit"}')


def task_priority(t: Task) -> int:
    """Priority class of a task (0 = default band; higher wins)."""
    return getattr(t.spec, "priority", 0) if t.spec is not None else 0


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def v_bucket(n: int) -> Optional[int]:
    for b in V_BUCKETS:
        if n <= b:
            return b
    return None


class CandidateSet:
    """Densified victims×nodes candidates for ONE pending group.

    Array shapes are the UNbucketed (n_nodes, per-node-truncated-to-V)
    host view; ops/preempt.py pads them to the static buckets before
    dispatch.  ``victims[j]`` maps victim slots back to mirror tasks.
    """

    __slots__ = ("infos", "ok", "free_cpu", "free_mem", "free_gen",
                 "vvalid", "vprio", "vcpu", "vmem", "vgen", "victims",
                 "vb", "n_candidates")

    def __init__(self, infos, ok, free_cpu, free_mem, vvalid, vprio,
                 vcpu, vmem, victims, vb, n_candidates,
                 free_gen=None, vgen=None):
        self.infos = infos
        self.ok = ok
        self.free_cpu = free_cpu
        self.free_mem = free_mem
        self.vvalid = vvalid
        self.vprio = vprio
        self.vcpu = vcpu
        self.vmem = vmem
        self.victims = victims
        self.vb = vb
        self.n_candidates = n_candidates
        # third resource column (single discrete generic kind): zeros
        # when the pending group demands none — the selection math is
        # then identical to the two-resource shape
        self.free_gen = free_gen if free_gen is not None \
            else np.zeros_like(free_cpu)
        self.vgen = vgen if vgen is not None else np.zeros_like(vcpu)


def preemptable_group(t: Task) -> bool:
    """Is this pending spec's infeasibility something preemption can
    fix?  Resource-shaped demand only — the waivers mirror the device
    planner's (``TPUPlanner._supported``)."""
    res = t.spec.resources.reservations if t.spec.resources else None
    if res is None or (not res.nano_cpus and not res.memory_bytes
                       and not res.generic):
        return False    # no resource demand: constraints, not capacity
    if len(res.generic) > 1 or any(
            g.res_type != GenericResourceKind.DISCRETE or g.value <= 0
            for g in res.generic):
        # narrowed waiver (ISSUE 12): ONE discrete generic kind rides
        # the selection's third resource column; multi-kind and NAMED
        # demands keep the host-bookkeeping waiver
        return False
    if t.endpoint and any(p.publish_mode == PublishMode.HOST
                          and p.published_port
                          for p in t.endpoint.ports):
        return False    # freed host ports are not modeled
    if t.spec.placement and t.spec.placement.max_replicas:
        # node eligibility is snapshotted once per group, but the
        # selection may stack several preemptors on one node — which
        # could breach max_replicas.  Waived, like the device path's
        # per-task-claim cases.
        return False
    c = t.spec.container
    if c is not None and any(m.type == MountType.CSI for m in c.mounts):
        return False    # volume scheduling stays on the host path
    return True


def demand_of(t: Task) -> Tuple[int, int, Optional[Tuple[str, int]]]:
    """(cpu, memory, generic) demand of a pending spec; ``generic`` is
    the single discrete (kind, value) pair ``preemptable_group`` admits,
    or None."""
    res = t.spec.resources.reservations if t.spec.resources else None
    if res is None:
        return 0, 0, None
    gen = None
    for g in res.generic:
        if g.res_type == GenericResourceKind.DISCRETE and g.value > 0:
            gen = (g.kind, int(g.value))
            break
    return int(res.nano_cpus), int(res.memory_bytes), gen


def _gen_amount(resources, kind: str) -> int:
    """Discrete units of ``kind`` in a Resources.generic list (NAMED
    units count 1 apiece — one name is one unit)."""
    total = 0
    for g in resources.generic:
        if g.kind != kind:
            continue
        total += 1 if g.res_type == GenericResourceKind.NAMED \
            else int(g.value)
    return total


def victim_slot_key(t: Task) -> tuple:
    """Anti-thrash cooldown key: one slot of one service (node-keyed for
    global services, like orchestrator slot tuples)."""
    if t.slot:
        return (t.service_id, t.slot, "")
    return (t.service_id, 0, t.node_id)


def build_candidates(sched, t: Task, prio: int,
                     excluded_ids, cooldowns: Dict[tuple, float],
                     cooldown: float,
                     skipped_cooldown: Optional[List[int]] = None,
                     gen_kind: Optional[str] = None
                     ) -> Optional[CandidateSet]:
    """Densify the mirror into the shared candidate arrays for pending
    spec ``t`` at priority ``prio``.  Returns None when no node has any
    eligible victim (nothing to select over).

    Node eligibility (``ok``) runs the host filter pipeline MINUS the
    resource filter — preemption exists to fix resource infeasibility,
    every other filter must already pass.  Victim eligibility: status
    RUNNING, desired <= COMPLETE (service tasks run at RUNNING, job
    tasks at COMPLETE), STRICTLY lower priority, not shut down by an
    earlier pick this tick, and the slot not inside its cooldown.
    """
    infos: List[NodeInfo] = list(sched.node_set.nodes.values())
    if not infos:
        return None
    n = len(infos)
    ts = now()

    pipe = Pipeline()
    pipe._checklist = [e for e in pipe._checklist
                       if not isinstance(e.f, ResourceFilter)]
    pipe.set_task(t)

    ok = np.zeros(n, bool)
    free_cpu = np.zeros(n, np.int64)
    free_mem = np.zeros(n, np.int64)
    free_gen = np.zeros(n, np.int64)
    per_node: List[List[Task]] = []
    max_v = 0
    n_candidates = 0
    skipped_cd = 0
    for j, info in enumerate(infos):
        node = info.node
        live = (node.status.state == NodeState.READY
                and node.spec.availability == NodeAvailability.ACTIVE)
        ok[j] = live and pipe.process(info)
        free_cpu[j] = info.available_resources.nano_cpus
        free_mem[j] = info.available_resources.memory_bytes
        if gen_kind is not None:
            free_gen[j] = _gen_amount(info.available_resources, gen_kind)
        cands: List[Task] = []
        if ok[j]:
            for vt in info.tasks.values():
                # the node mirror's task objects serve membership and
                # reservations — their STATUS can be stale (add_task
                # only swaps objects on desired-state flips), so the
                # current row comes from the scheduler's all_tasks view
                vt = sched.all_tasks.get(vt.id, vt)
                if vt.status.state != TaskState.RUNNING:
                    continue
                if vt.desired_state > TaskState.COMPLETE:
                    continue
                if task_priority(vt) >= prio:
                    continue    # NEVER equal-or-higher
                if vt.id in excluded_ids:
                    continue
                stamp = cooldowns.get(victim_slot_key(vt))
                if stamp is not None and ts - stamp < cooldown:
                    skipped_cd += 1
                    continue
                cands.append(vt)
            # deterministic order: cheapest (lowest priority) first,
            # task id as the tie-break — the prefix the selection eats
            cands.sort(key=lambda v: (task_priority(v), v.id))
        per_node.append(cands)
        n_candidates += len(cands)
        if len(cands) > max_v:
            max_v = len(cands)
    if skipped_cooldown is not None:
        skipped_cooldown.append(skipped_cd)
    if n_candidates == 0:
        return None
    vb = v_bucket(max_v)
    if vb is None:
        vb = V_BUCKETS[-1]    # truncate: keep the V cheapest per node
    vvalid = np.zeros((vb, n), bool)
    vprio = np.zeros((vb, n), np.int32)
    vcpu = np.zeros((vb, n), np.int64)
    vmem = np.zeros((vb, n), np.int64)
    vgen = np.zeros((vb, n), np.int64)
    victims: List[List[Task]] = []
    for j, cands in enumerate(per_node):
        cands = cands[:vb]
        victims.append(cands)
        for s, vt in enumerate(cands):
            res = task_reservations(vt)
            vvalid[s, j] = True
            # weight clamp: negative bands weigh like 0, huge bands
            # saturate — selection ORDER already used the raw priority
            vprio[s, j] = min(max(task_priority(vt), 0), PRIO_CLAMP)
            vcpu[s, j] = int(res.nano_cpus)
            vmem[s, j] = int(res.memory_bytes)
            if gen_kind is not None:
                # victims free their RESERVED generics of the demanded
                # kind (reservation-side, like cpu/memory)
                vgen[s, j] = _gen_amount(res, gen_kind)
    return CandidateSet(infos, ok, free_cpu, free_mem, vvalid, vprio,
                        vcpu, vmem, victims, vb, n_candidates,
                        free_gen=free_gen, vgen=vgen)


def select_victims_host(cand: CandidateSet, cpu_d: int, mem_d: int,
                        gen_d: int, n_picks: int, budget: int
                        ) -> List[Tuple[int, int]]:
    """The oracle: sequential greedy picks over the candidate arrays.
    Returns [(node_index, prefix_len)] — the EXACT integers the device
    kernel must reproduce (tests/test_preemption.py fuzzes the pair,
    including the generic-resource column).  ``gen_d`` is the single
    discrete generic demand (0 = none; the third column is then inert).
    """
    vvalid = cand.vvalid
    V, N = vvalid.shape
    used = np.zeros((V, N), bool)
    extra_cpu = [0] * N    # python ints: exact, like the i64 kernel
    extra_mem = [0] * N
    extra_gen = [0] * N
    picks: List[Tuple[int, int]] = []
    budget_rem = budget
    for _ in range(n_picks):
        best = None    # (cost, nvict, j, m)
        for j in range(N):
            if not cand.ok[j]:
                continue
            have_cpu = int(cand.free_cpu[j]) + extra_cpu[j]
            have_mem = int(cand.free_mem[j]) + extra_mem[j]
            have_gen = int(cand.free_gen[j]) + extra_gen[j]
            cost = 0
            nvict = 0
            m = None
            if have_cpu >= cpu_d and have_mem >= mem_d \
                    and have_gen >= gen_d:
                m = 0
            else:
                for s in range(V):
                    if vvalid[s, j] and not used[s, j]:
                        have_cpu += int(cand.vcpu[s, j])
                        have_mem += int(cand.vmem[s, j])
                        have_gen += int(cand.vgen[s, j])
                        cost += int(cand.vprio[s, j]) + 1
                        nvict += 1
                    if have_cpu >= cpu_d and have_mem >= mem_d \
                            and have_gen >= gen_d:
                        m = s + 1
                        break
            if m is None:
                continue
            key = (cost, nvict, j)
            if best is None or key < best[:3]:
                best = (cost, nvict, j, m)
        if best is None:
            break    # infeasible: same demand for every pick, so stop
        cost, nvict, j, m = best
        if nvict > budget_rem:
            break    # budget exhausted: stop (device mirrors this)
        freed_cpu = 0
        freed_mem = 0
        freed_gen = 0
        for s in range(m):
            if vvalid[s, j] and not used[s, j]:
                used[s, j] = True
                freed_cpu += int(cand.vcpu[s, j])
                freed_mem += int(cand.vmem[s, j])
                freed_gen += int(cand.vgen[s, j])
        extra_cpu[j] += freed_cpu - cpu_d
        extra_mem[j] += freed_mem - mem_d
        extra_gen[j] += freed_gen - gen_d
        budget_rem -= nvict
        picks.append((j, m))
    return picks


def replay_pick_victims(cand: CandidateSet,
                        picks: List[Tuple[int, int]]
                        ) -> List[Tuple[int, List[Task]]]:
    """Expand (node, prefix_len) picks into concrete victim tasks —
    the same used-mask replay the selection ran, so host- and device-
    computed picks map to identical task sets."""
    used: Dict[int, set] = {}
    out: List[Tuple[int, List[Task]]] = []
    for j, m in picks:
        taken = used.setdefault(j, set())
        chosen = [cand.victims[j][s] for s in range(m)
                  if s < len(cand.victims[j]) and s not in taken]
        taken.update(s for s in range(m) if s < len(cand.victims[j]))
        out.append((j, chosen))
    return out


class PreemptSupervisor:
    """Per-scheduler preemption policy state: budget, cooldowns, latency
    stamps, and the obs exports.  All time flows through
    ``models.types.now()`` (virtual under the sim)."""

    def __init__(self, budget: Optional[int] = None,
                 cooldown: Optional[float] = None):
        self.budget = budget if budget is not None \
            else _env_int("SWARM_PREEMPT_BUDGET", DEFAULT_BUDGET)
        self.cooldown = cooldown if cooldown is not None \
            else _env_float("SWARM_PREEMPT_COOLDOWN", DEFAULT_COOLDOWN)
        #: slot key -> stamp of the last preemption (anti-thrash)
        self.cooldowns: Dict[tuple, float] = {}
        #: victim task id -> commit stamp, resolved by the scheduler's
        #: event mirror when the victim reaches a terminal state
        self.pending_exits: Dict[str, float] = {}
        #: victims shut down earlier in the current tick (excluded from
        #: later groups' candidate sets — their resources are already
        #: promised to committed preemptors)
        self.shut_this_tick: set = set()
        self.stats = {"preemptions": 0, "preempted_tasks_placed": 0,
                      "inversions": 0, "budget_stops": 0}

    # ------------------------------------------------------------ accounting

    def begin_tick(self) -> int:
        self.shut_this_tick = set()
        # prune expired cooldown stamps: entries are only ever compared
        # against the window, so dropping them here keeps the dict
        # bounded by the slots preempted within one cooldown period
        ts = now()
        expired = [k for k, stamp in self.cooldowns.items()
                   if ts - stamp >= self.cooldown]
        for k in expired:
            del self.cooldowns[k]
        return self.budget

    def note_preemptions(self, victims: List[Task], prio: int) -> None:
        ts = now()
        for vt in victims:
            self.cooldowns[victim_slot_key(vt)] = ts
            self.pending_exits[vt.id] = ts
            self.shut_this_tick.add(vt.id)
        self.stats["preemptions"] += len(victims)
        _metrics.counter('swarm_preemptions{reason="priority"}',
                         len(victims))

    def note_skipped(self, reason: str, delta: int = 1) -> None:
        if delta > 0:
            _metrics.counter(f'swarm_preempt_skipped{{reason="{reason}"}}',
                             delta)

    def observe_commit_latency(self, t0: float) -> None:
        _COMMIT_TIMER.observe(now() - t0)

    def observe_task_gone(self, task_id: str) -> None:
        """Scheduler event hook: a preempted victim reached a terminal
        state (or was deleted) — close its exit-latency window."""
        stamp = self.pending_exits.pop(task_id, None)
        if stamp is not None:
            _EXIT_TIMER.observe(now() - stamp)

    def export_inversions(self, count: int) -> None:
        """``swarm_priority_inversion``: pending higher-priority tasks a
        feasible victim set existed for this tick but that were NOT
        placed (budget stop / commit failure) — the signal the
        ``priority_inversion`` health check judges."""
        self.stats["inversions"] += count
        _metrics.gauge("swarm_priority_inversion", float(count))
