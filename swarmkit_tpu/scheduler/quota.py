"""Multi-tenant quota enforcement: the admission-side half of QoS.

Tenants are priority bands with resource quotas (``ClusterSpec.tenants``,
keyed by the ``swarm.tenant`` service-annotation label).  The scheduler
enforces them **at admission**, before placement, so a misbehaving
tenant's scale-up is clamped instead of being fought by preemption
after the fact:

* ``TenantLedger`` recomputes each tenant's committed usage (cpu/memory
  reservations + task count of assigned, live tasks) from the
  scheduler's mirror at tick start, then charges every admitted group
  as the tick walks the priority-ordered queue — so group g+1 of a
  tenant sees group g's admission, exactly like the fused planner's
  carry sees earlier groups' placements.
* A group whose tenant cannot admit even ONE task is *blocked*: it
  still flows to the placement paths, where the **quota mask column**
  (device program, ``NodeInputs.quota_ok`` — ops/kernel.py) or the
  ``QuotaFilter`` (host pipeline, below) rejects every node, so the
  tasks carry the proper ``no suitable node (over tenant quota ...)``
  diagnostics on both paths, byte-identically.
* A group the tenant can only partially afford is *clamped*: the
  scheduler splits it, schedules the admitted prefix, and defers the
  remainder with a quota message (``swarm_quota_clamps{tenant=}``).

Verdicts are stamped once per (group, tick) at admission time and
never recomputed downstream — an admitted group's own charge must not
flip its verdict between admission and placement.  Preassigned
(global-service) tasks are outside quota scope: their node is fixed
before the scheduler sees them.

The sim's ``quota-never-exceeded`` invariant (sim/invariants.py)
re-derives usage from committed store events and fails the run the
moment any tenant's committed usage exceeds its quota.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..models.objects import Cluster, Task
from ..models.types import TaskState, TenantQuota
from ..utils.metrics import registry as _metrics
from .filters import Filter
from .nodeinfo import task_reservations

log = logging.getLogger("quota")

#: the service-annotation label naming a service's tenant; propagated
#: onto every task via ``Task.service_annotations`` (orchestrator
#: common.new_task), so tenant resolution never needs a store lookup
TENANT_LABEL = "swarm.tenant"


def task_tenant(t: Task) -> str:
    """Tenant of a task ("" = untenanted, never quota'd)."""
    ann = t.service_annotations
    if ann is None or not ann.labels:
        return ""
    return ann.labels.get(TENANT_LABEL, "")


def group_key(t: Task) -> tuple:
    """Identity of the scheduling group a task belongs to — the same
    (service, spec-version) keying the scheduler's pending queue uses,
    with one-off (version-less) tasks as their own singletons.  Both
    the admission clamp and the QuotaFilter derive it from a task, so
    a verdict stamped at admission is found again at placement."""
    sv = t.spec_version
    if sv is None:
        return (t.service_id, -1, t.id)
    return (t.service_id, sv.index, "")


class TenantLedger:
    """Per-tick tenant usage + admission arithmetic (all integers).

    ``begin_tick`` rebuilds the committed-usage base from the
    scheduler's fresh task mirror; ``admit``/``charge`` run as the tick
    admits groups in priority order.  ``blocked_groups`` holds the
    frozen per-group verdicts for this tick (see module docstring).
    """

    def __init__(self) -> None:
        self.quotas: Dict[str, TenantQuota] = {}
        #: tenant -> [nano_cpus, memory_bytes, tasks] committed+charged
        self.used: Dict[str, List[int]] = {}
        #: group keys whose tenant was exhausted at admission this tick
        self.blocked_groups: set = set()
        #: group key -> tasks charged at admission this tick; the
        #: preemption pass adds this back when computing a group's
        #: headroom (its own charge must not read as "no quota left" —
        #: the charge IS its entitlement)
        self.group_charges: Dict[tuple, int] = {}
        #: task ids deferred by a partial clamp this tick (they carry
        #: NO charge — preemption headroom must not count them)
        self.deferred_tasks: set = set()
        self.stats = {"clamped_tasks": 0, "blocked_groups": 0}

    # ------------------------------------------------------------- config

    def load_cluster(self, cluster: Optional[Cluster]) -> None:
        self.quotas = dict(cluster.spec.tenants) if cluster is not None \
            else {}

    @property
    def active(self) -> bool:
        return bool(self.quotas)

    # ------------------------------------------------------------ per tick

    def begin_tick(self, all_tasks: Dict[str, Task]) -> None:
        """Rebuild the usage base from the scheduler's mirror: assigned,
        live (desired <= COMPLETE, status <= RUNNING) tasks of quota'd
        tenants.  Also exports ``swarm_tenant_quota_used{tenant=}`` —
        the fullest constrained dimension as a fraction of its quota."""
        self.blocked_groups = set()
        self.group_charges = {}
        self.deferred_tasks = set()
        if not self.quotas:
            self.used = {}
            return
        used: Dict[str, List[int]] = {}
        for t in all_tasks.values():
            if (not t.node_id
                    or t.desired_state > TaskState.COMPLETE
                    or t.status.state > int(TaskState.RUNNING)
                    or t.status.state < int(TaskState.ASSIGNED)):
                continue
            tenant = task_tenant(t)
            if tenant not in self.quotas:
                continue
            res = task_reservations(t)
            row = used.setdefault(tenant, [0, 0, 0])
            row[0] += int(res.nano_cpus)
            row[1] += int(res.memory_bytes)
            row[2] += 1
        self.used = used
        for tenant, q in self.quotas.items():
            row = used.get(tenant, (0, 0, 0))
            frac = 0.0
            for have, limit in ((row[0], q.nano_cpus),
                                (row[1], q.memory_bytes),
                                (row[2], q.max_tasks)):
                if limit > 0:
                    frac = max(frac, have / limit)
            _metrics.gauge(
                f'swarm_tenant_quota_used{{tenant="{tenant}"}}',
                round(frac, 6))

    def admit(self, tenant: str, cpu_d: int, mem_d: int,
              k: int) -> Optional[int]:
        """How many tasks of per-task demand (cpu_d, mem_d) the tenant's
        remaining quota admits, capped at ``k``.  None = the tenant has
        no quota (unlimited).  A quota'd tenant whose tasks reserve
        nothing is only bounded by ``max_tasks``."""
        q = self.quotas.get(tenant)
        if q is None:
            return None
        row = self.used.get(tenant, (0, 0, 0))
        rem = k
        if q.max_tasks > 0:
            rem = min(rem, q.max_tasks - row[2])
        if q.nano_cpus > 0 and cpu_d > 0:
            rem = min(rem, (q.nano_cpus - row[0]) // cpu_d)
        if q.memory_bytes > 0 and mem_d > 0:
            rem = min(rem, (q.memory_bytes - row[1]) // mem_d)
        return max(int(rem), 0)

    def charge(self, tenant: str, cpu_d: int, mem_d: int,
               n: int) -> None:
        """Charge ``n`` admitted tasks.  Optimistic: a task that later
        fails to place re-enters the next tick's recomputed base, so an
        in-tick overcharge can only under-admit, never over-admit."""
        if tenant not in self.quotas or n <= 0:
            return
        row = self.used.setdefault(tenant, [0, 0, 0])
        row[0] += cpu_d * n
        row[1] += mem_d * n
        row[2] += n

    def uncharge(self, tenant: str, cpu_d: int, mem_d: int,
                 n: int) -> None:
        """Roll back ``n`` tasks' charge — the gang admission path
        (scheduler/gang.py) charges every member group up front and
        must return the whole charge when the unit defers on a
        shortfall, so later groups in the same tick see the quota the
        gang did NOT consume.  (``charge`` ignores n <= 0 by design,
        hence the dedicated inverse.)"""
        if tenant not in self.quotas or n <= 0:
            return
        row = self.used.setdefault(tenant, [0, 0, 0])
        row[0] -= cpu_d * n
        row[1] -= mem_d * n
        row[2] -= n

    # ------------------------------------------------------------ verdicts

    def note_group_charge(self, t: Task, n: int) -> None:
        key = group_key(t)
        self.group_charges[key] = self.group_charges.get(key, 0) + n

    def group_charge(self, t: Task) -> int:
        return self.group_charges.get(group_key(t), 0)

    def preempt_headroom(self, t: Task, cpu_d: int, mem_d: int,
                         group: Dict[str, Task]) -> Optional[int]:
        """Tasks of this group the tenant's quota allows the PREEMPTION
        pass to place: the live remainder (`admit`) plus the group's
        own phantom charge — each admitted-but-unplaced task in
        ``group`` was already charged at admission, so its entitlement
        carries over (quota-deferred tasks carry none).  None = no
        quota (unlimited)."""
        tenant = task_tenant(t)
        admit = self.admit(tenant, cpu_d, mem_d, len(group))
        if admit is None:
            return None
        phantom = sum(1 for tid in group
                      if tid not in self.deferred_tasks)
        return admit + min(phantom, self.group_charge(t))

    def block_group(self, t: Task) -> None:
        self.blocked_groups.add(group_key(t))
        self.stats["blocked_groups"] += 1

    def group_blocked(self, t: Task) -> bool:
        """Frozen admission verdict for the group ``t`` belongs to (the
        quota mask column and the host QuotaFilter both read this)."""
        return group_key(t) in self.blocked_groups


class QuotaFilter(Filter):
    """Host-pipeline half of the quota mask: enabled only for groups the
    ledger blocked at admission, where it rejects every node — the same
    all-false column the device program carries, so host and device
    placements (and their ``no suitable node`` explanations) stay
    byte-identical.  Appended LAST in the checklist, matching the quota
    row's position in the kernel's short-circuit failure counts."""

    def __init__(self, ledger: TenantLedger):
        self.ledger = ledger

    def set_task(self, t: Task) -> bool:
        return self.ledger.active and self.ledger.group_blocked(t)

    def check(self, n) -> bool:
        return False

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "over tenant quota on 1 node"
        return f"over tenant quota on {nodes} nodes"
