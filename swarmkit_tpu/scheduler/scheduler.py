"""The scheduler: assigns PENDING tasks to nodes.

Reference: manager/scheduler/scheduler.go.

Event-loop object over the store: mirrors tasks/nodes in memory, debounces
commit events (50ms gap, 1s max), groups unassigned tasks by (service,
spec-version), builds a spread-preference tree per group, round-robins tasks
over sorted candidate nodes re-filtering after every placement, then commits
ASSIGNED states in batched transactions with node-version conflict rollback.

A pluggable ``batch_planner`` seam lets the TPU path (ops/planner.py) replace
the per-group tree walk with a device-computed placement while event
handling, commit logic, and the host path stay identical — the Filter/
Pipeline gating strategy called for in SURVEY.md §5.8.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from ..models.objects import Cluster, Node, Service, Task, Volume
from ..models.types import (
    Resources, TaskState, TaskStatus, now,
)
from ..obs import planes as _planes
from ..obs.trace import tracer
from ..utils.metrics import registry as _metrics
from ..utils.pipeline import default_pipeline_depth
from ..state.events import Event, EventCommit, EventSnapshotRestore
from ..state.store import Batch, ByName, MemoryStore, ReadTx
from ..state.watch import Closed
from . import gang as gang_mod
from . import genericresource
from . import preempt as preempt_mod
from . import strategy as strategy_mod
from .deltatrack import DeltaTracker
from .filters import Pipeline, VolumesFilter
from .nodeinfo import MAX_FAILURES, NodeInfo, task_reservations
from .nodeset import DecisionTree, NodeSet
from .preempt import PreemptSupervisor, task_priority
from .quota import QuotaFilter, TenantLedger, task_tenant
from .volumes import VolumeSet

log = logging.getLogger("scheduler")

COMMIT_DEBOUNCE_GAP = 0.050   # reference: scheduler.go:149-155
MAX_LATENCY = 1.0

# cached Timer references (Registry.reset() resets in place)
_TICK_TIMER = _metrics.timer("swarm_scheduler_tick_latency")
_COMMIT_TIMER = _metrics.timer("swarm_scheduler_commit_latency")


class SchedulingDecision:
    __slots__ = ("old", "new")

    def __init__(self, old: Task, new: Task):
        self.old = old
        self.new = new


class _TickCommitter:
    """One tick's commit pipeline: group drafts commit on a dedicated
    thread, in submission (= planning) order, while the main thread
    builds and dispatches the next group's device plan — the host-commit
    half of the plan/commit overlap (docs/architecture.md "Pipelined
    scheduling").

    The tick is only acked after ``close()``: every submitted draft has
    resolved, commit results aggregated, so conflict rollback and
    re-enqueue run exactly as the serial path's end-of-tick handling.
    Once leadership is observed lost, remaining drafts fail WITHOUT
    touching the store — no in-flight device plan may commit after
    leadership loss (asserted by the sim's pipelined-commit scenario).
    """

    __slots__ = ("_sched", "_q", "_tickets", "_thread", "_resolved")

    def __init__(self, sched: "Scheduler"):
        self._sched = sched
        self._q: "queue.Queue" = queue.Queue()
        self._tickets: List[dict] = []
        self._thread: Optional[threading.Thread] = None
        self._resolved = 0   # tickets resolve strictly FIFO

    def submit(self, draft: List[Tuple[List[Task], List[str], str]]
               ) -> None:
        ticket = {"draft": draft, "done": threading.Event(),
                  "committed": 0, "failed": [], "missing": []}
        self._tickets.append(ticket)
        _metrics.gauge("swarm_scheduler_chunk_inflight",
                       float(len(self._tickets) - self._resolved))
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="sched-commit", daemon=True)
            self._thread.start()
        self._q.put(ticket)

    def throttle(self, max_inflight: int) -> None:
        """Bounded depth: block until at most ``max_inflight`` submitted
        drafts remain unresolved.  Tickets resolve in submission order
        (single FIFO committer), so a monotonic resolved-prefix index
        keeps this O(1) amortized per call."""
        while len(self._tickets) - self._resolved > max_inflight:
            self._tickets[self._resolved]["done"].wait()
            self._resolved += 1
        _metrics.gauge("swarm_scheduler_chunk_inflight",
                       float(len(self._tickets) - self._resolved))

    @staticmethod
    def _fail_all(ticket: dict) -> None:
        ticket["failed"] = [
            (old, nid) for olds, nids, _ in ticket["draft"]
            for old, nid in zip(olds, nids)]

    def _lost_leadership(self) -> bool:
        """Fail-fast check before touching the store.  The epoch
        comparison is the load-bearing one: the tick's drafts are pinned
        to the leadership epoch captured at tick start, so a deposal —
        even a depose-and-re-elect flap this thread never observes as a
        role change — fences the remaining drafts.  (The proposer
        re-checks the same epoch pre-WAL and at commit delivery, so this
        racy fast-path can only ever fail early, never admit late.)"""
        proposer = self._sched.store._proposer
        if proposer is None:
            return False
        if not getattr(proposer, "is_leader", True):
            return True
        tick_epoch = self._sched._tick_epoch
        return (tick_epoch is not None
                and getattr(proposer, "leadership_epoch", None)
                != tick_epoch)

    def _run(self) -> None:
        while True:
            ticket = self._q.get()
            if ticket is None:
                return
            sched = self._sched
            try:
                if self._lost_leadership():
                    self._fail_all(ticket)
                else:
                    n = sum(len(olds)
                            for olds, _, _ in ticket["draft"])
                    t0 = now()
                    with tracer.span("sched.commit", "sched",
                                     decisions=n):
                        c, _, f = sched._commit_draft(
                            ticket["draft"], want_ids=False,
                            missing_out=ticket["missing"])
                    dt = now() - t0
                    sched.stats["commit_seconds"] += dt
                    _COMMIT_TIMER.observe(dt)
                    ticket["committed"] = c
                    ticket["failed"] = f
            except Exception:
                log.exception("pipelined block commit failed")
                self._fail_all(ticket)
            finally:
                ticket["done"].set()

    def close(self) -> Tuple[int, List[Tuple[Task, str]]]:
        """Join the committer, then run the deferred vanished-task
        cleanup on the calling (main) thread; returns (committed count,
        failed (mirror task, node_id) pairs) across all drafts."""
        if self._thread is not None:
            self._q.put(None)
            self._thread.join()
        committed = sum(t["committed"] for t in self._tickets)
        failed = [p for t in self._tickets for p in t["failed"]]
        for t in self._tickets:
            for old, nid in t["missing"]:
                self._sched._on_block_missing(old, nid)
        return committed, failed


class Scheduler:
    def __init__(self, store: MemoryStore,
                 batch_planner=None,
                 debounce_gap: float = COMMIT_DEBOUNCE_GAP,
                 max_latency: float = MAX_LATENCY,
                 pipeline_depth: Optional[int] = None,
                 preempt_budget: Optional[int] = None,
                 preempt_cooldown: Optional[float] = None,
                 tick_budget_s: Optional[float] = None):
        self.store = store
        # bounded-depth plan/commit software pipeline: while group i's
        # draft commits on the committer thread, group i+1's device plan
        # is dispatched and computes.  1 = strictly serial tick
        # (SWARM_PIPELINE_DEPTH escape hatch); placements are
        # byte-identical either way (tests/test_pipeline.py).
        self.pipeline_depth = (pipeline_depth if pipeline_depth is not None
                               else default_pipeline_depth())
        # commit-event debounce windows (reference: scheduler.go:149-155);
        # injectable so tests and the simulator control latency precisely
        self.debounce_gap = debounce_gap
        self.max_latency = max_latency
        self.unassigned_tasks: Dict[str, Task] = {}
        # count of unassigned tasks in a positive priority band: while
        # it is nonzero, a lower-priority task reaching RUNNING is a
        # tick trigger — new preemption capacity just materialized
        # (without this, a starving high-priority group would wait for
        # an unrelated create/delete/node event to retry)
        self._prio_pending = 0
        # incremental (service, spec-version) grouping of the unassigned
        # queue: maintained at enqueue/dequeue time so tick() does not pay
        # a per-task grouping pass (reference groups in tick,
        # scheduler.go:438-462 — same result, amortized differently)
        self.unassigned_groups: Dict[Optional[Tuple[str, int]],
                                     Dict[str, Task]] = {}
        self.pending_preassigned_tasks: Dict[str, Task] = {}
        self.preassigned_tasks: set = set()
        # streaming-scheduler delta feed: node create/update/remove and
        # task commit/exit events (this loop's existing block-aware
        # subscription) fold into per-node dirty bits the planner's
        # resident device-input state refreshes from (ops/streaming.py)
        self.delta = DeltaTracker()
        self.node_set = NodeSet()
        self.node_set.tracker = self.delta
        self.all_tasks: Dict[str, Task] = {}
        self.pipeline = Pipeline()
        self.volumes = VolumeSet()
        self.batch_planner = batch_planner
        # columnar commit draft: one (mirror tasks, node_ids, status
        # message) column triple per planned group, accumulated by the
        # device planner when the store allows block commits
        # (store.commit_task_block); committed as array-shaped calls per
        # tick instead of per-task objects
        self.block_draft: List[Tuple[List[Task], List[str], str]] = []
        self.block_mode = False

        # priority preemption (scheduler/preempt.py): budget, anti-thrash
        # cooldowns, and obs exports.  SWARM_PREEMPTION=0 disables the
        # pass wholesale; with every priority at the default 0 band the
        # pass is a no-op either way (positive priority opts a service
        # into preempting).
        import os as _os
        self.preempt = PreemptSupervisor(budget=preempt_budget,
                                         cooldown=preempt_cooldown)
        self.preempt_enabled = \
            _os.environ.get("SWARM_PREEMPTION", "") != "0"

        # overload protection: per-tick deadline budget (seconds).  A
        # tick that exceeds it mid-walk commits what it planned CLEANLY
        # and re-enqueues the remaining groups for the next tick —
        # backlog converts to bounded per-tick latency instead of one
        # unboundedly long tick that starves heartbeats and fan-out.
        # Virtual-clock sims never trip it (the clock is frozen inside
        # a control step), so sim runs stay byte-deterministic.
        _budget = _os.environ.get("SWARM_TICK_BUDGET_S", "")
        self.tick_budget_s = tick_budget_s if tick_budget_s is not None \
            else (float(_budget) if _budget else None)
        self._tick_deadline: Optional[float] = None

        # multi-tenant quota plane (scheduler/quota.py): admission-side
        # clamp + the host half of the quota mask column.  The filter
        # rides the shared pipeline so the host oracle's short-circuit
        # failure counts (and explanations) match the device kernel's
        # quota row.  SWARM_TENANT_QUOTA=0 disables enforcement
        # wholesale; with no tenants on the ClusterSpec the plane is a
        # no-op either way.
        self.quota = TenantLedger()
        self.quota_enabled = \
            _os.environ.get("SWARM_TENANT_QUOTA", "") != "0"
        self._quota_filter = QuotaFilter(self.quota)
        self.pipeline.add_filter(self._quota_filter)

        # gang scheduling (scheduler/gang.py): all-or-nothing placement
        # units + the pipeline gate.  Pure no-op bookkeeping until a
        # spec opts in via Placement.gang / ServiceSpec.depends_on.
        self.gang = gang_mod.GangState()

        # leadership epoch captured at tick/preassigned-pass start; every
        # commit of that pass is pinned to it (None = unfenced proposer)
        self._tick_epoch: Optional[int] = None
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # stats for benchmarking / tests (bounded: long-lived managers
        # tick many times per second)
        from collections import deque
        self.stats = {"ticks": 0, "decisions": 0, "commit_seconds": 0.0,
                      "tick_seconds": deque(maxlen=1024)}

        # scheduler-plane saturation probe (obs/planes.py): backlog
        # depth and oldest pending age, read lazily at window-roll time.
        # plane() is resolved per call — planes.reset() rebinds the
        # table and a cached PlaneStats would go stale.  The probe holds
        # a WEAKREF: it must never pin a dead scheduler's task graph
        # (bench builds one per trial).  Co-resident schedulers (HA
        # tests): last constructed owns the probe.
        import weakref
        _ref = weakref.ref(self)

        def _sched_probe():
            sched = _ref()
            if sched is None:
                return {}
            tasks = list(sched.unassigned_tasks.values())
            depth = float(len(tasks)
                          + len(sched.pending_preassigned_tasks))
            oldest = 0.0
            stamps = [t.status.timestamp for t in tasks
                      if t.status is not None and t.status.timestamp]
            if stamps:
                oldest = max(0.0, now() - min(stamps))
            return {"depth": depth, "oldest_age": oldest}
        _planes.plane(_planes.SCHEDULER).set_probe(_sched_probe)

    # ------------------------------------------------------------------ setup

    def _setup_tasks_list(self, tx: ReadTx) -> None:
        clusters = tx.find(Cluster, ByName("default"))
        self.quota.load_cluster(clusters[0] if clusters else None)
        for volume in tx.find(Volume):
            if volume.volume_info and volume.volume_info.volume_id:
                self.volumes.add_or_update_volume(volume)

        tasks_by_node: Dict[str, Dict[str, Task]] = {}
        for t in tx.find(Task):
            if (t.status.state < TaskState.PENDING
                    or t.status.state > TaskState.RUNNING):
                continue
            if (t.status.state == TaskState.PENDING
                    and t.desired_state > TaskState.COMPLETE):
                # updated/removed before ever being assigned
                continue
            self.all_tasks[t.id] = t
            if not t.node_id:
                self._enqueue(t)
                continue
            if t.status.state == TaskState.PENDING:
                self.preassigned_tasks.add(t.id)
                self.pending_preassigned_tasks[t.id] = t
                continue
            self.volumes.reserve_task_volumes(t)
            tasks_by_node.setdefault(t.node_id, {})[t.id] = t

        self._build_node_set(tx, tasks_by_node)

    def _build_node_set(self, tx: ReadTx,
                        tasks_by_node: Dict[str, Dict[str, Task]]) -> None:
        for n in tx.find(Node):
            resources = Resources()
            if n.description and n.description.resources:
                resources = n.description.resources
            self.node_set.add_or_update_node(
                NodeInfo(n, tasks_by_node.get(n.id), resources))

    # ------------------------------------------------------------- event loop

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="scheduler",
                                        daemon=True)
        self._thread.start()

    def run(self) -> None:
        try:
            self.pipeline.add_filter(VolumesFilter(self.volumes))
            # accepts_blocks: EventTaskBlocks on this store are this
            # scheduler's OWN commits (it is the only block producer on a
            # leader) — mirrors intentionally keep the pre-assignment
            # objects, so blocks are ignored below instead of being
            # expanded into len(block) synthesized self-echo events
            _, sub = self.store.view_and_watch(
                lambda tx: self._setup_tasks_list(tx),
                accepts_blocks=True)
            try:
                self._process_preassigned_tasks()
                self.tick()

                debounce_started: Optional[float] = None
                tick_required = False

                while not self._stop.is_set():
                    if debounce_started is None:
                        timeout = 0.2
                    else:
                        deadline = min(debounce_started + self.max_latency,
                                       self._last_event + self.debounce_gap)
                        timeout = max(0.0, deadline - now())
                    try:
                        event = sub.get(timeout=timeout) if timeout > 0 else None
                    except TimeoutError:
                        event = None
                    except Closed:
                        return

                    if event is None:
                        if debounce_started is not None:
                            if len(self.pending_preassigned_tasks) > 0:
                                self._process_preassigned_tasks()
                            if tick_required:
                                self.tick()
                                tick_required = False
                            debounce_started = None
                        continue

                    if isinstance(event, EventCommit):
                        self._last_event = now()
                        if debounce_started is None:
                            debounce_started = self._last_event
                    elif isinstance(event, EventSnapshotRestore):
                        self._resync()
                        tick_required = True
                    elif isinstance(event, Event):
                        tick_required |= self._handle_event(event)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    _last_event = 0.0

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)

    def _resync(self) -> None:
        self.unassigned_tasks.clear()
        self.unassigned_groups.clear()
        self._prio_pending = 0
        self.pending_preassigned_tasks.clear()
        self.preassigned_tasks.clear()
        self.all_tasks.clear()
        self.node_set = NodeSet()
        self.node_set.tracker = self.delta
        # a wholesale re-mirror invalidates every resident row at once
        self.delta.require_full("resync-store")
        # clear in place: the pipeline's VolumesFilter holds a reference
        self.volumes.clear()
        self.store.view(lambda tx: self._setup_tasks_list(tx))

    def _handle_event(self, ev: Event) -> bool:
        obj = ev.obj
        if isinstance(obj, Task):
            if ev.action == "create":
                return self._create_task(obj)
            if ev.action == "update":
                return self._update_task(obj)
            return self._delete_task(self.all_tasks.get(obj.id, obj))
        if isinstance(obj, Node):
            if ev.action == "delete":
                self.node_set.remove(obj.id)
                return False
            self._create_or_update_node(obj)
            return True
        if isinstance(obj, Volume) and ev.action == "update":
            if obj.volume_info and obj.volume_info.volume_id:
                self.volumes.add_or_update_volume(obj)
                return True
        if isinstance(obj, Cluster) and ev.action != "delete" \
                and obj.spec.annotations.name == "default":
            # live quota changes (the "default" cluster only — the one
            # _setup_tasks_list reads; any other Cluster object must
            # not wipe the quota table): a raised quota may unblock
            # pending tenant work, so the next tick must run
            self.quota.load_cluster(obj)
            return True
        return False

    # --------------------------------------------------------- state mirror

    def _enqueue(self, t: Task) -> None:
        self.unassigned_tasks[t.id] = t
        if task_priority(t) > 0:
            self._prio_pending += 1
        sv = t.spec_version
        key = (t.service_id, sv.index) if sv is not None else None
        self.unassigned_groups.setdefault(key, {})[t.id] = t

    def _dequeue(self, task_id: str) -> None:
        t = self.unassigned_tasks.pop(task_id, None)
        if t is not None:
            if task_priority(t) > 0:
                self._prio_pending -= 1
            sv = t.spec_version
            key = (t.service_id, sv.index) if sv is not None else None
            group = self.unassigned_groups.get(key)
            if group is not None:
                group.pop(task_id, None)
                if not group:
                    del self.unassigned_groups[key]

    def _create_task(self, t: Task) -> bool:
        if (t.status.state < TaskState.PENDING
                or t.status.state > TaskState.RUNNING):
            return False
        self.all_tasks[t.id] = t
        if not t.node_id:
            self._enqueue(t)
            return True
        if t.status.state == TaskState.PENDING:
            self.preassigned_tasks.add(t.id)
            self.pending_preassigned_tasks[t.id] = t
            return False
        info = self.node_set.node_info(t.node_id)
        if info is not None:
            info.add_task(t)
        return False

    def _update_task(self, t: Task) -> bool:
        if t.status.state < TaskState.PENDING:
            return False
        old = self.all_tasks.get(t.id)
        if t.status.state > TaskState.RUNNING:
            if old is None:
                return False
            if (t.status.state != old.status.state
                    and t.status.state in (TaskState.FAILED,
                                           TaskState.REJECTED)):
                if t.id not in self.preassigned_tasks:
                    info = self.node_set.node_info(t.node_id)
                    if info is not None:
                        info.task_failed(t)
            self._delete_task(old)
            return True
        if not t.node_id:
            if old is not None:
                self._delete_task(old)
            self.all_tasks[t.id] = t
            self._enqueue(t)
            return True
        if t.status.state == TaskState.PENDING:
            if old is not None:
                self._delete_task(old)
            self.preassigned_tasks.add(t.id)
            self.all_tasks[t.id] = t
            self.pending_preassigned_tasks[t.id] = t
            return False
        self.all_tasks[t.id] = t
        info = self.node_set.node_info(t.node_id)
        if info is not None:
            info.add_task(t)
        # a lower-priority task reaching RUNNING while a positive band
        # starves is preemption capacity arriving: tick.  Capacity-
        # blocked gang units (ROADMAP item 7 residual) are starved the
        # same way despite their 0 band, so they extend the trigger.
        return ((self._prio_pending > 0 or bool(self.gang.blocked))
                and t.status.state == TaskState.RUNNING)

    def _delete_task(self, t: Task) -> bool:
        # a preempted victim leaving the mirror (terminal status, or the
        # orchestrator's dead-slot delete) closes its exit-latency window
        self.preempt.observe_task_gone(t.id)
        self.all_tasks.pop(t.id, None)
        self.preassigned_tasks.discard(t.id)
        self.pending_preassigned_tasks.pop(t.id, None)
        self._dequeue(t.id)
        for va in t.volumes:
            self.volumes.release_volume(va.id, t.id)
        info = self.node_set.node_info(t.node_id)
        if info is not None and info.remove_task(t):
            return True
        return False

    def _create_or_update_node(self, n: Node) -> None:
        info = self.node_set.node_info(n.id)
        if n.description and n.description.resources:
            resources = n.description.resources.copy()
            if info is not None:
                for task in info.tasks.values():
                    reservations = task_reservations(task)
                    resources.memory_bytes -= reservations.memory_bytes
                    resources.nano_cpus -= reservations.nano_cpus
                    genericresource.consume(resources.generic,
                                            task.assigned_generic_resources)
        else:
            resources = Resources()
        if info is None:
            self.node_set.add_or_update_node(NodeInfo(n, None, resources))
        else:
            info.node = n
            info.available_resources = resources
            # in-place node swap bypasses the NodeInfo mutation hooks
            self.delta.mark(n.id)

    # -------------------------------------------------------------- decisions

    def _process_preassigned_tasks(self) -> None:
        with tracer.span("sched.preassigned", "sched",
                         pending=len(self.pending_preassigned_tasks)):
            self._process_preassigned_inner()

    def _process_preassigned_inner(self) -> None:
        self._tick_epoch = getattr(self.store._proposer,
                                   "leadership_epoch", None)
        decisions: Dict[str, SchedulingDecision] = {}
        pending = list(self.pending_preassigned_tasks.values())
        planner = self.batch_planner
        self.block_mode = self.store.supports_block_commit
        if planner is not None and hasattr(planner, "validate_preassigned"):
            # large same-spec batches (global services during a storm)
            # validate in one fused device call; whatever the device path
            # can't model (volumes, ports, small batches, rejections
            # needing per-filter explanations) falls through to the host
            # loop below.  Keyed like the group queues (_enqueue): tasks
            # of different spec versions have different constraints and
            # reservations and must not share one densified group
            by_spec: Dict[tuple, list] = {}
            for t in pending:
                key = (t.service_id,
                       t.spec_version.index if t.spec_version else -1)
                by_spec.setdefault(key, []).append(t)
            pending = []
            for group in by_spec.values():
                pending.extend(
                    planner.validate_preassigned(self, group, decisions))
        _, committed_ids, block_failed = self._commit_block_draft()
        for tid in committed_ids:
            self.pending_preassigned_tasks.pop(tid, None)
        for old, nid in block_failed:
            self.all_tasks[old.id] = old
            info = self.node_set.node_info(nid)
            if info is not None:
                info.remove_task(old)
        for t in pending:
            new_t = self._task_fit_node(t, t.node_id)
            if new_t is None:
                continue
            decisions[t.id] = SchedulingDecision(t, new_t)
        successful, failed = self._apply_scheduling_decisions(decisions)
        for d in successful:
            if d.new.status.state == TaskState.ASSIGNED:
                self.pending_preassigned_tasks.pop(d.old.id, None)
        for d in failed:
            self.all_tasks[d.old.id] = d.old
            info = self.node_set.node_info(d.new.node_id)
            if info is not None:
                info.remove_task(d.new)
            for va in d.new.volumes:
                self.volumes.release_volume(va.id, d.new.id)

    def tick(self) -> int:
        """Schedule the unassigned queue; returns number of decisions."""
        from ..utils.gctune import paused_gc
        t0 = now()
        with paused_gc(), tracer.span("sched.tick", "sched") as sp:
            n = self._tick_inner()
            if sp is not None:
                sp.args = {"decisions": n}
        _dt = now() - t0
        _TICK_TIMER.observe(_dt)
        _planes.plane(_planes.SCHEDULER).note_busy(_dt)
        return n

    def _tick_inner(self) -> int:
        t0 = now()
        self.stats["ticks"] += 1
        self._tick_deadline = (t0 + self.tick_budget_s
                               if self.tick_budget_s else None)
        # one reign per tick: every draft planned below commits under the
        # epoch read here or not at all (leadership-epoch fencing)
        self._tick_epoch = getattr(self.store._proposer,
                                   "leadership_epoch", None)
        self.block_mode = self.store.supports_block_commit
        # tenant-quota base usage for this tick, recomputed from the
        # fresh mirror; admission charges accumulate on top of it as
        # the priority-ordered queue below is walked
        if self.quota_enabled:
            self.quota.begin_tick(self.all_tasks)
            self._ensure_quota_filter_last()
        decisions: Dict[str, SchedulingDecision] = {}

        # groups are maintained incrementally by _enqueue/_dequeue; take
        # them over wholesale — failures re-enqueue into fresh dicts during
        # the scheduling phase below
        with tracer.span("sched.batch_build", "sched") as sp:
            groups = self.unassigned_groups
            self.unassigned_groups = {}
            self.unassigned_tasks.clear()
            self._prio_pending = 0    # failures re-enqueue (re-count)
            one_off_tasks = groups.pop(None, {})
            if sp is not None:
                sp.args = {"groups": len(groups),
                           "one_off": len(one_off_tasks)}

        # gang units leave the normal walk and admit atomically first
        # (scheduler/gang.py) — a pure no-op extraction when no task
        # opts in, so non-gang ticks stay byte-identical
        gang_units = gang_mod.take_gangs(groups, one_off_tasks)
        if gang_units or self.gang.blocked or self.gang.first_pending:
            self.gang.prune([k for k, _ in gang_units])
        n_gang = (gang_mod.admit_gangs(self, gang_units, decisions)
                  if gang_units else 0)

        planner = self.batch_planner
        use_pipeline = (self.pipeline_depth > 1 and self.block_mode
                        and planner is not None
                        and hasattr(planner, "dispatch_group"))
        pipe_block = 0       # block decisions already committed in-pipeline
        pipe_committed = 0
        pipe_failed: List[Tuple[Task, str]] = []
        if planner is not None and hasattr(planner, "begin_tick"):
            planner.begin_tick(self)
        try:
            if use_pipeline:
                pipe_block, pipe_committed, pipe_failed = \
                    self._run_group_pipeline(groups, one_off_tasks,
                                             decisions)
            else:
                self._run_groups_serial(groups, one_off_tasks, decisions)
        finally:
            if planner is not None and hasattr(planner, "end_tick"):
                planner.end_tick()

        n_decisions = n_gang + len(decisions) + pipe_block + sum(
            len(olds) for olds, _, _ in self.block_draft)
        with tracer.span("sched.commit", "sched", decisions=n_decisions):
            t_commit = now()
            n_committed, _, block_failed = self._commit_block_draft(
                want_ids=False)
            residual = n_committed or block_failed
            n_committed += pipe_committed
            block_failed = pipe_failed + block_failed
            for old, nid in block_failed:
                # mirror rollback (remove_task never reads node_id, so the
                # pre-assignment object works) + requeue for the next tick
                self.all_tasks[old.id] = old
                info = self.node_set.node_info(nid)
                if info is not None:
                    info.remove_task(old)
                self._enqueue(old)
            if residual:
                # pipelined drafts were timed on the committer thread;
                # only a residual serial commit lands here
                dt_block = now() - t_commit
                self.stats["commit_seconds"] += dt_block
                # the columnar path commits here, not through
                # _apply_scheduling_decisions — feed the timer both ways
                _COMMIT_TIMER.observe(dt_block)
            _, failed = self._apply_scheduling_decisions(decisions)
        for d in failed:
            self.all_tasks[d.old.id] = d.old
            info = self.node_set.node_info(d.new.node_id)
            if info is not None:
                info.remove_task(d.new)
            for va in d.new.volumes:
                self.volumes.release_volume(va.id, d.new.id)
            self._enqueue(d.old)

        # priority preemption: higher-priority groups the normal pass
        # left infeasible may evict strictly-lower-priority running work
        n_decisions += self._preempt_pass()

        if not decisions and self.volumes.frees_pending:
            # releases without new decisions (task shutdowns) must still
            # queue node-unpublish for now-unused volumes (the decisions
            # path runs free_volumes in its own finally)
            self.volumes.frees_pending = False
            try:
                self.store.batch(self.volumes.free_volumes)
            except Exception:
                log.exception("freeing volumes failed")

        self.stats["decisions"] += n_decisions
        self.stats["tick_seconds"].append(now() - t0)
        return n_decisions

    def _tick_groups(self, groups, one_off_tasks, decisions=None
                     ) -> Iterable[Dict[str, Task]]:
        """The tick's task groups in scheduling order, with entries that
        were assigned out-of-band since enqueue dropped — one code path
        shared by the serial loop and the pipeline so group order (and
        therefore commit/event order) is identical in both modes.

        Order is the PRIORITY-ORDERED pending queue: higher priority
        classes schedule first so a constrained tick spends its capacity
        on the important band.  The sort is stable over the insertion-
        ordered group dicts (one-off tasks after service groups, as
        before), so ties — including the all-default-priority case every
        pre-priority workload is — keep the exact historical order and
        placements stay byte-deterministic."""
        entries: List[Tuple[int, Dict[str, Task]]] = []
        for group in groups.values():
            stale = [tid for tid, t in group.items()
                     if t is None or t.node_id]
            for tid in stale:
                del group[tid]
            if group:
                entries.append(
                    (task_priority(next(iter(group.values()))), group))
        for t in one_off_tasks.values():
            if t is not None and not t.node_id:
                entries.append((task_priority(t), {t.id: t}))
        entries.sort(key=lambda e: -e[0])
        yielded = 0
        for i, (_, group) in enumerate(entries):
            # tick deadline budget: once over budget — and with at
            # least one group yielded, so a single huge group still
            # makes progress — the rest of the queue re-enqueues for
            # the next tick and this tick commits partially.  The
            # priority sort above means the deferral always lands on
            # the LOWEST bands of this tick's queue.
            if (self._tick_deadline is not None and yielded > 0
                    and now() >= self._tick_deadline):
                deferred = 0
                for _, g in entries[i:]:
                    for t in g.values():
                        self._enqueue(t)
                        deferred += 1
                self.stats["partial_ticks"] = \
                    self.stats.get("partial_ticks", 0) + 1
                self.stats["deferred_tasks"] = \
                    self.stats.get("deferred_tasks", 0) + deferred
                _metrics.counter("swarm_scheduler_partial_ticks")
                _planes.plane(_planes.SCHEDULER).defer(deferred)
                log.info("tick budget %.3fs exceeded: %d tasks "
                         "deferred to the next tick",
                         self.tick_budget_s, deferred)
                return
            # pipeline gate (scheduler/gang.py): a group whose service
            # awaits an upstream DAG stage defers before admission so
            # gated work never consumes quota or placement capacity
            group = gang_mod.pipeline_gate(self, group, decisions)
            if not group:
                continue
            group = self._quota_admit(group, decisions)
            if group:
                yielded += 1
                yield group

    # -------------------------------------------------------- tenant quota

    def _ensure_quota_filter_last(self) -> None:
        """The QuotaFilter's checklist position is load-bearing: it
        must be LAST so the host pipeline's short-circuit failure
        counts (and the resulting 'no suitable node' explanation) match
        the device kernel's quota row, which is evaluated after every
        other mask.  Filters appended later (VolumesFilter in run()/the
        sim) would otherwise displace it — re-pin it each tick."""
        checklist = self.pipeline._checklist
        if checklist and checklist[-1].f is self._quota_filter:
            return
        for i, entry in enumerate(checklist):
            if entry.f is self._quota_filter:
                checklist.append(checklist.pop(i))
                return

    def _quota_admit(self, group: Dict[str, Task],
                     decisions) -> Dict[str, Task]:
        """Admission clamp for one group (scheduler/quota.py): charge
        fully-admitted groups, split partially-affordable ones (the
        deferred remainder re-queues with a quota message), and stamp a
        frozen BLOCKED verdict on groups whose tenant cannot admit even
        one task — those still flow to placement, where the quota mask
        column / QuotaFilter rejects every node so both paths produce
        identical ``over tenant quota`` diagnostics."""
        ledger = self.quota
        if not self.quota_enabled or not ledger.active:
            return group
        t0 = next(iter(group.values()))
        tenant = task_tenant(t0)
        res = task_reservations(t0)
        cpu_d, mem_d = int(res.nano_cpus), int(res.memory_bytes)
        admit = ledger.admit(tenant, cpu_d, mem_d, len(group))
        if admit is None:
            return group            # untenanted / unlimited
        if admit >= len(group):
            ledger.charge(tenant, cpu_d, mem_d, len(group))
            ledger.note_group_charge(t0, len(group))
            return group
        if admit <= 0:
            # exhausted: nothing charged — the mask/filter rejects the
            # whole group at placement (diagnostics parity by design)
            ledger.block_group(t0)
            return group
        # partial: admit the insertion-order prefix (deterministic),
        # defer the rest
        items = list(group.items())
        admitted = dict(items[:admit])
        ledger.charge(tenant, cpu_d, mem_d, admit)
        ledger.note_group_charge(t0, admit)
        self._quota_defer(tenant, items[admit:], decisions)
        return admitted

    def _quota_defer(self, tenant: str, items, decisions) -> None:
        """Defer clamped tasks: quota message + re-queue for the next
        tick (the _no_suitable_node discipline, with a quota-specific
        error so operators see the clamp, not a capacity problem)."""
        n = len(items)
        self.stats["quota_clamps"] = self.stats.get("quota_clamps", 0) + n
        _metrics.counter(f'swarm_quota_clamps{{tenant="{tenant}"}}', n)
        ts = now()
        for task_id, _t in items:
            self.quota.deferred_tasks.add(task_id)
        for task_id, t in items:
            new_t = t.copy()
            new_t.status.timestamp = ts
            new_t.status.err = f'over tenant quota (tenant "{tenant}")'
            self.all_tasks[task_id] = new_t
            self._enqueue(new_t)
            if decisions is not None:
                decisions[task_id] = SchedulingDecision(t, new_t)

    def _run_group_pipeline(self, groups, one_off_tasks, decisions
                            ) -> Tuple[int, int, List[Tuple[Task, str]]]:
        """Software-pipelined scheduling phase: while group i's draft
        commits on the committer thread (raft propose/apply, store
        overlay writes), group i+1's inputs are densified and its device
        plan dispatched — the device computes during the host commit
        instead of idling.  Placement order, mirror mutation order, and
        commit order all match the serial path exactly (each group's
        plan is fetched and applied before the next group's inputs are
        built), so placements are byte-identical; only the wall-clock
        interleaving changes.  Returns (block decisions drafted,
        committed count, failed pairs); the tick is acked only after the
        last draft resolved.

        Runs of >= 2 consecutive fusable groups take the FUSED
        many-service path (ops/fusedbatch.py): one densify + one
        scan-over-groups program per chunk instead of a round-trip per
        group, with the same per-group drafts flowing to the committer
        in the same order — a fused tick's store/event stream is
        byte-identical to the per-group tick's.
        """
        planner = self.batch_planner
        committer = _TickCommitter(self)
        inflight: Optional[Tuple[object, Dict[str, Task]]] = None
        n_block = 0
        glist = list(self._tick_groups(groups, one_off_tasks, decisions))
        can_fuse = hasattr(planner, "probe_fused_run")
        i = 0
        try:
            while i < len(glist):
                # probe reads only task specs + planner routing state, so
                # it is safe with a per-group plan still in flight
                specs = (planner.probe_fused_run(self, glist, i)
                         if can_fuse else [])
                if len(specs) >= 2:
                    if inflight is not None:
                        n_block += self._finish_inflight(
                            inflight, decisions, committer)
                        inflight = None
                    consumed, fused_block, spilled = self._run_fused(
                        specs, decisions, committer)
                    n_block += fused_block
                    i += consumed
                    if consumed and not spilled:
                        continue
                    # spilled at glist[i] (re-fusing replans against the
                    # same node state and deterministically spills again)
                    # or the run could not build/dispatch: glist[i]
                    # falls through to the per-group path below
                group = glist[i]
                i += 1
                if inflight is not None:
                    n_block += self._finish_inflight(inflight, decisions,
                                                     committer)
                    inflight = None
                handle = planner.dispatch_group(self, group, decisions)
                if handle is None:
                    # not device-planned: host oracle, synchronously (no
                    # plan is in flight here, so mirror mutation order
                    # matches the serial path)
                    self._schedule_group_host(group, decisions)
                else:
                    inflight = (handle, group)
            if inflight is not None:
                n_block += self._finish_inflight(inflight, decisions,
                                                 committer)
                inflight = None
        finally:
            if inflight is not None and hasattr(planner,
                                                "discard_inflight"):
                planner.discard_inflight()
            committed, failed = committer.close()
        return n_block, committed, failed

    def _run_groups_serial(self, groups, one_off_tasks, decisions) -> None:
        """Serial scheduling phase (pipeline_depth == 1, or no pipelined
        planner): groups schedule synchronously and drafts commit at
        tick end.  Fusable runs still take the fused many-service path —
        it is thread-free (chunk fetches block inline), so the sim's
        deterministic depth-1 control plane exercises the exact fused
        program production runs."""
        planner = self.batch_planner
        can_fuse = (planner is not None
                    and hasattr(planner, "probe_fused_run"))
        glist = list(self._tick_groups(groups, one_off_tasks, decisions))
        i = 0
        while i < len(glist):
            specs = (planner.probe_fused_run(self, glist, i)
                     if can_fuse else [])
            if len(specs) >= 2:
                consumed, _, spilled = self._run_fused(specs, decisions,
                                                       committer=None)
                i += consumed
                if consumed and not spilled:
                    continue
                # spilled group (glist[i]) goes per-group below
            self._schedule_task_group(glist[i], decisions)
            i += 1

    def _run_fused(self, specs, decisions,
                   committer: Optional[_TickCommitter]
                   ) -> Tuple[int, int, bool]:
        """Drive one fused run to completion: fetch each chunk (the next
        chunk computes on device meanwhile), apply its groups in order,
        and hand each group's draft to the committer (pipelined mode) or
        leave it on ``block_draft`` for the end-of-tick commit (serial
        mode) — exactly where the per-group path puts it.  Returns
        (groups consumed, block decisions drafted to the committer,
        spilled); a spill or a dead run stops early and the caller
        continues per-group from the first unconsumed group — without
        re-probing a spilled group for fusion, which would replan it
        against identical node state and spill again."""
        planner = self.batch_planner
        run = planner.dispatch_fused_run(self, specs)
        if run is None:
            return 0, 0, False
        n_block = 0
        consumed = 0
        try:
            while True:
                out = planner.fetch_fused_chunk(run)
                if out is None:
                    break
                xs, fcs, spills, start, count = out
                for j in range(count):
                    gi = start + j
                    if bool(spills[j]):
                        # exact reference parity requires the host
                        # oracle for this group; later groups were
                        # planned against a placement that no longer
                        # happens, so the run aborts here
                        planner.note_fused_spill(run)
                        return consumed, n_block, True
                    planner.apply_fused_group(run, gi, xs[j], fcs[j],
                                              decisions)
                    group = run.specs[gi].group
                    if group:
                        self._no_suitable_node(
                            group, decisions,
                            explanation=getattr(planner,
                                                "last_explanation", ""))
                    consumed += 1
                    if committer is not None and self.block_draft:
                        draft, self.block_draft = self.block_draft, []
                        n_block += sum(len(olds)
                                       for olds, _, _ in draft)
                        committer.submit(draft)
                        committer.throttle(max(1,
                                               self.pipeline_depth - 1))
        finally:
            planner.abort_fused_run(run)
        return consumed, n_block, False

    def _finish_inflight(self, inflight, decisions,
                         committer: _TickCommitter) -> int:
        """Fetch + apply an in-flight device plan, then hand its draft
        to the commit pipeline.  Returns the number of block decisions
        drafted for the group."""
        handle, group = inflight
        planner = self.batch_planner
        handled = planner.fetch_group(handle)
        if not handled:
            # spill: exact reference parity requires the host oracle's
            # convergence loop for this group (same as the serial path)
            self._schedule_group_host(group, decisions)
            return 0
        if group:
            self._no_suitable_node(
                group, decisions,
                explanation=getattr(planner, "last_explanation", ""))
        if not self.block_draft:
            return 0
        draft, self.block_draft = self.block_draft, []
        n = sum(len(olds) for olds, _, _ in draft)
        committer.submit(draft)
        # bounded depth: one plan in flight on the device + at most
        # depth-1 unacked commits behind it
        committer.throttle(max(1, self.pipeline_depth - 1))
        return n

    # ----------------------------------------------------------- preemption

    def _preempt_pass(self) -> int:
        """Evict strictly-lower-priority running tasks for pending
        groups the normal scheduling pass could not place (the
        priority & preemption subsystem — scheduler/preempt.py hosts
        the oracle and policy state, ops/preempt.py the device kernel).

        Each successful pick commits its victims' shutdown AND the
        preemptor's assignment in one store transaction (the store pins
        the write to the leadership epoch at commit start; the pass
        itself refuses to run once the tick's reign is over), so the
        orchestrators observe an atomic swap and requeue the victims'
        slots at their own — lower — priority.  Returns the number of
        preemptor tasks placed."""
        sup = self.preempt
        if sup is None or not self.preempt_enabled:
            return 0
        entries: List[Tuple[int, Dict[str, Task]]] = []
        for key, group in self.unassigned_groups.items():
            if not group:
                continue
            if key is None:
                # the one-off bucket is heterogeneous (no shared spec):
                # each task is its own singleton group, exactly as the
                # normal pass schedules them (_tick_groups)
                for t in group.values():
                    if task_priority(t) > 0 \
                            or gang_mod.preempt_entitled(self, t):
                        entries.append((task_priority(t), {t.id: t}))
                continue
            t0 = next(iter(group.values()))
            prio = task_priority(t0)
            # positive bands may preempt; so may capacity-blocked or
            # aged gang units in the 0 band (ROADMAP item 7 residual:
            # the trigger predicate used to require priority > 0, so a
            # quota-entitled gang starved forever behind it)
            if prio > 0 or gang_mod.preempt_entitled(self, t0):
                entries.append((prio, group))
        if not entries:
            sup.export_inversions(0)
            return 0
        proposer = self.store._proposer
        if proposer is not None \
                and getattr(proposer, "leadership_epoch", None) \
                != self._tick_epoch:
            # the tick's reign is over: nothing may commit under it
            sup.export_inversions(0)
            return 0
        entries.sort(key=lambda e: -e[0])    # stable: insertion ties
        budget_rem = sup.begin_tick()
        device = getattr(self.batch_planner, "select_victims", None)
        placed_total = 0
        inversions = 0
        t_pass = now()
        for prio, group in entries:
            if budget_rem <= 0:
                sup.note_skipped("budget", len(group))
                inversions += len(group)
                continue
            t0 = next(iter(group.values()))
            if not preempt_mod.preemptable_group(t0):
                sup.note_skipped("unsupported", len(group))
                continue
            if gang_mod.is_gated(self, t0):
                # a pipeline-gated group cannot schedule even with the
                # capacity: evicting victims for it would be pure loss
                sup.note_skipped("gated", len(group))
                continue
            cpu_d, mem_d, gen_d = preempt_mod.demand_of(t0)
            headroom = None
            if self.quota_enabled and self.quota.active:
                # a tenant at (or over) its quota must not preempt its
                # way past it — QoS clamps at admission, full stop.
                # Headroom counts the group's OWN admission charge back
                # in: tasks already admitted (and charged) this tick are
                # entitled to preempt their way to placement.
                headroom = self.quota.preempt_headroom(
                    t0, cpu_d, mem_d, group)
                if headroom is not None and headroom <= 0:
                    sup.note_skipped("quota", len(group))
                    continue
            skipped_cd: List[int] = []
            cand = preempt_mod.build_candidates(
                self, t0, prio, sup.shut_this_tick, sup.cooldowns,
                sup.cooldown, skipped_cd,
                gen_kind=gen_d[0] if gen_d else None)
            if skipped_cd and skipped_cd[0]:
                sup.note_skipped("cooldown", skipped_cd[0])
            if cand is None:
                continue
            # host and device run the SAME capped pick count — the
            # shared-iteration contract the differential fuzz pins.
            # A quota'd tenant's picks are additionally capped at its
            # headroom (remaining quota + the group's own charge).
            n_picks = min(len(group), budget_rem)
            if headroom is not None:
                n_picks = min(n_picks, headroom)
            gen_val = gen_d[1] if gen_d else 0
            picks = None
            if device is not None:
                picks = device(cand, cpu_d, mem_d, gen_val, n_picks,
                               budget_rem)
            if picks is None:
                picks = preempt_mod.select_victims_host(
                    cand, cpu_d, mem_d, gen_val, n_picks, budget_rem)
            if picks:
                # gang groups evict ONLY (assign=False): per-pick
                # assignment would commit a strict subset of the gang;
                # the freed capacity lets the unit place atomically on
                # the next tick instead
                placed, victims_n = self._commit_preemption(
                    group, t0, prio, cand, picks,
                    assign=not gang_mod.is_gang(t0))
                budget_rem -= victims_n
                placed_total += placed
                if placed and self.quota_enabled and self.quota.active:
                    # keep the ledger honest for later same-tenant
                    # groups this pass: placements consume the group's
                    # phantom charge first; only the excess (fresh
                    # quota headroom) is new usage to charge
                    consumed = min(placed, self.quota.group_charge(t0))
                    self.quota.note_group_charge(t0, -consumed)
                    extra = placed - consumed
                    if extra > 0:
                        self.quota.charge(task_tenant(t0), cpu_d,
                                          mem_d, extra)
            # still-pending positive-priority tasks with live lower-
            # priority candidates = the inversion signal the
            # priority_inversion health check judges.  Count against
            # the unassigned queue, not the (possibly temporary
            # singleton) group dict.
            inversions += sum(1 for tid in group
                              if tid in self.unassigned_tasks)
        if placed_total:
            sup.observe_commit_latency(t_pass)
        sup.export_inversions(inversions)
        self.stats["preemptions"] = sup.stats["preemptions"]
        return placed_total

    def _commit_preemption(self, group: Dict[str, Task], t0: Task,
                           prio: int, cand, picks,
                           assign: bool = True
                           ) -> Tuple[int, int]:
        """Commit the selected picks: one atomic transaction per pick
        (victims' desired SHUTDOWN + preemption marker, preemptor's
        ASSIGNED write), each re-validated against the store row so a
        racing agent update skips the pick instead of corrupting it.
        ``assign=False`` (gang groups) commits the victims' shutdown
        WITHOUT placing the preemptor — a gang member may only commit
        with its whole unit (scheduler/gang.py), so the pass frees the
        capacity and the unit places atomically on a later tick.
        Returns (preemptors placed, victims shut down)."""
        from ..models.types import Annotations
        expanded = preempt_mod.replay_pick_victims(cand, picks)
        items = list(group.items())
        sup = self.preempt
        placed = 0
        victims_total = 0
        ts = now()
        for idx, (j, victims) in enumerate(expanded):
            if idx >= len(items):
                break
            tid, _mirror = items[idx]
            node_id = cand.infos[j].id
            result: Dict[str, object] = {}

            def cb(tx, tid=tid, node_id=node_id, victims=victims,
                   result=result):
                cur = None
                if assign:
                    cur = tx.get(Task, tid)
                    if cur is None or cur.node_id \
                            or cur.status.state != TaskState.PENDING \
                            or cur.desired_state > TaskState.COMPLETE:
                        return
                vrows = []
                for vt in victims:
                    vcur = tx.get(Task, vt.id)
                    if vcur is None \
                            or vcur.desired_state > TaskState.COMPLETE \
                            or vcur.status.state != TaskState.RUNNING \
                            or vcur.node_id != vt.node_id:
                        return    # a victim changed under us: skip pick
                    vrows.append(vcur)
                for vcur in vrows:
                    nv = vcur.copy()
                    nv.desired_state = TaskState.SHUTDOWN
                    # replace-don't-mutate: fresh Annotations so the
                    # committed marker never aliases the old object
                    nv.annotations = Annotations(
                        name=nv.annotations.name,
                        labels={**nv.annotations.labels,
                                "swarm.preempted.at": f"{ts:.3f}",
                                "swarm.preempted.by": t0.service_id,
                                "swarm.preempted.by.prio": str(prio),
                                "swarm.preempted.prio": str(
                                    task_priority(vcur))},
                        indices=dict(nv.annotations.indices))
                    tx.update(nv)
                if assign:
                    new_t = cur.copy()
                    new_t.node_id = node_id
                    new_t.status = TaskStatus(
                        state=TaskState.ASSIGNED, timestamp=ts,
                        message="scheduler assigned task to node "
                                "(preempted lower-priority tasks)")
                    tx.update(new_t)
                    result["task"] = new_t
                result["victims"] = victims

            try:
                self.store.update(cb)
            except Exception:
                # leadership loss or store failure: the pass stops; the
                # group's remainder stays pending (counted as inversions)
                log.exception("preemption transaction failed")
                break
            if "victims" not in result:
                # the pick was skipped (preemptor or a victim changed
                # under us): STOP — later picks' feasibility may depend
                # on this pick's evictions (same-node surplus carry),
                # so committing them could overcommit the node.  The
                # group's remainder retries next tick against fresh
                # state.
                break
            if assign:
                new_t = result["task"]
                self._dequeue(tid)
                self.all_tasks[tid] = new_t
                info = self.node_set.node_info(new_t.node_id)
                if info is not None:
                    info.add_task(new_t)
                placed += 1
            sup.note_preemptions(result["victims"], prio)
            victims_total += len(result["victims"])
        return placed, victims_total

    def _commit_block_draft(self, want_ids: bool = True
                            ) -> Tuple[int, Optional[List[str]],
                                       List[Tuple[Task, str]]]:
        """Commit the columnar assignment draft through
        store.commit_task_block — arrays end-to-end, no per-task objects
        (they materialize lazily on read).  Returns (committed count,
        committed task ids or None when ``want_ids`` is False, failed
        (mirror task, node_id) pairs for rollback)."""
        draft = self.block_draft
        if not draft:
            return 0, [] if want_ids else None, []
        self.block_draft = []
        return self._commit_draft(draft, want_ids)

    def _on_block_missing(self, old: Task, nid: str) -> None:
        # the draft already planted the task on the assigned node's
        # mirror (membership + reservations) — clean THAT node, not
        # old.node_id (which is empty pre-assignment)
        info = self.node_set.node_info(nid)
        if info is not None:
            info.remove_task(old)
        self._delete_task(self.all_tasks.get(old.id, old))

    def _commit_draft(self, draft: List[Tuple[List[Task], List[str], str]],
                      want_ids: bool = True,
                      missing_out: Optional[List[Tuple[Task, str]]] = None
                      ) -> Tuple[int, Optional[List[str]],
                                 List[Tuple[Task, str]]]:
        """Commit an explicit draft list (the body of
        ``_commit_block_draft``, callable from the tick committer with
        drafts taken off ``block_draft`` at submit time).

        ``missing_out``: when given (the committer-thread path),
        vanished-task cleanup is DEFERRED — (old, nid) pairs are
        appended for the main thread to process at tick end via
        ``_on_block_missing`` — because it mutates scheduler mirrors,
        which must not happen concurrently with the main thread's
        planning.  The serial path runs it inline (same thread)."""
        node_info = self.node_set.node_info
        raw_get = self.store.raw_get

        def on_missing(old: Task, nid: str) -> None:
            if missing_out is not None:
                missing_out.append((old, nid))
                return
            self._on_block_missing(old, nid)

        def on_assigned(old: Task, nid: str) -> bool:
            # stored task already >= ASSIGNED: commit only if our view of
            # the node is current (node-version conflict check)
            info = node_info(nid)
            if info is None:
                return False
            node = raw_get(Node, nid)
            return (node is not None and node.meta.version.index
                    == info.node.meta.version.index)

        n_committed = 0
        committed_ids: Optional[List[str]] = [] if want_ids else None
        failed: List[Tuple[Task, str]] = []
        for olds, nids, msg in draft:
            try:
                c, f = self.store.commit_task_block(
                    olds, nids, int(TaskState.ASSIGNED), msg,
                    on_missing, on_assigned,
                    guard_state=int(TaskState.ASSIGNED),
                    epoch=self._tick_epoch)
            except Exception:
                log.exception("scheduler block commit failed")
                failed.extend(zip(olds, nids))
                continue
            n_committed += len(c)
            if committed_ids is not None:
                committed_ids.extend(olds[i].id for i in c)
            failed.extend((olds[i], nids[i]) for i in f)
        return n_committed, committed_ids, failed

    def _apply_scheduling_decisions(
            self, decisions: Dict[str, SchedulingDecision]
    ) -> Tuple[List[SchedulingDecision], List[SchedulingDecision]]:
        """Commit ASSIGNED states (reference: scheduler.go:490).

        Decisions without volume attachments take the store's columnar
        bulk-commit path (one validation callback per task, no per-task
        transaction objects or defensive copies); volume-carrying decisions
        keep the transactional path that also stages volume publish updates.
        """
        if not decisions:
            return [], []
        t0 = now()
        try:
            return self._apply_decisions_inner(decisions)
        finally:
            dt = now() - t0
            self.stats["commit_seconds"] += dt
            _COMMIT_TIMER.observe(dt)

    def _apply_decisions_inner(self, decisions):
        fast: List[SchedulingDecision] = []
        fast_tasks: List[Task] = []
        slow: Dict[str, SchedulingDecision] = {}
        for tid, d in decisions.items():
            new = d.new
            if new.volumes:
                slow[tid] = d
            else:
                fast.append(d)
                fast_tasks.append(new)

        successful: List[SchedulingDecision] = []
        failed: List[SchedulingDecision] = []
        if fast:
            s, f = self._apply_decisions_bulk(fast, fast_tasks)
            successful.extend(s)
            failed.extend(f)
        if slow:
            s, f = self._apply_decisions_tx(slow)
            successful.extend(s)
            failed.extend(f)
        elif fast:
            # the tx path frees volumes in its finally; mirror that here
            self.store.batch(self.volumes.free_volumes)
        return successful, failed

    def _apply_decisions_bulk(self, fast: List[SchedulingDecision],
                              fast_tasks: List[Task]):
        """Columnar commit via store.bulk_update_tasks; same semantic
        checks as commit_one below."""
        node_info = self.node_set.node_info
        raw_get = self.store.raw_get

        def on_assigned(new: Task) -> bool:
            # stored task already >= ASSIGNED: commit only if our view of
            # the node is current (node-version conflict check)
            info = node_info(new.node_id)
            if info is None:
                return False
            node = raw_get(Node, new.node_id)
            return (node is not None and node.meta.version.index
                    == info.node.meta.version.index)

        try:
            committed, failed_idx = self.store.bulk_update_tasks(
                fast_tasks, on_missing=self._delete_task,
                on_assigned=on_assigned, guard_state=TaskState.ASSIGNED,
                epoch=self._tick_epoch)
            return ([fast[i] for i in committed],
                    [fast[i] for i in failed_idx])
        except Exception:
            log.exception("scheduler bulk commit failed")
            return [], list(fast)

    def _apply_decisions_tx(
            self, decisions: Dict[str, SchedulingDecision]
    ) -> Tuple[List[SchedulingDecision], List[SchedulingDecision]]:
        successful: List[SchedulingDecision] = []
        failed: List[SchedulingDecision] = []
        try:
            if not decisions:
                return successful, failed

            def commit_one(tx, decision: SchedulingDecision) -> None:
                t = tx.get(Task, decision.old.id)
                if t is None:
                    self._delete_task(decision.new)
                    return
                new_status = decision.new.status
                old_status = t.status
                if (old_status.state == new_status.state
                        and old_status.message == new_status.message
                        and old_status.err == new_status.err):
                    return
                if old_status.state >= TaskState.ASSIGNED:
                    # already assigned by someone else; check node version
                    info = self.node_set.node_info(decision.new.node_id)
                    if info is None:
                        failed.append(decision)
                        return
                    node = tx.get(Node, decision.new.node_id)
                    if (node is None or node.meta.version.index
                            != info.node.meta.version.index):
                        failed.append(decision)
                        return
                volumes_to_update = []
                for va in decision.new.volumes:
                    v = tx.get(Volume, va.id)
                    if v is None:
                        failed.append(decision)
                        return
                    if v.spec.availability != 0:  # not ACTIVE
                        failed.append(decision)
                        return
                    if not any(ps.node_id == decision.new.node_id
                               for ps in v.publish_status):
                        v = v.copy()
                        from ..models.types import VolumePublishStatus
                        v.publish_status.append(VolumePublishStatus(
                            node_id=decision.new.node_id,
                            state=VolumePublishStatus.State.PENDING_PUBLISH))
                        volumes_to_update.append(v)
                # decision.new carries the mirror's version: if the task
                # changed in the store after the scheduler mirrored it (e.g.
                # an orchestrator bumped desired_state during the debounce
                # window), tx.update raises SequenceConflict and the
                # decision fails instead of overwriting the concurrent
                # write (reference: scheduler.go:607-611).
                try:
                    tx.update(decision.new)
                except Exception:
                    failed.append(decision)
                    return
                for v in volumes_to_update:
                    tx.update(v)
                successful.append(decision)

            # Batch bounds each transaction/raft proposal by actual change
            # count (decisions may add volume updates beyond one change each)
            def cb(batch: Batch) -> None:
                for decision in decisions.values():
                    batch.update(
                        lambda tx, d=decision: commit_one(tx, d))

            self.store.batch(cb)
            return successful, failed
        except Exception:
            # Reference-parity behavior (scheduler.go:639-644): on a batch
            # error, treat everything as failed so tasks are rolled back in
            # the mirror and re-enqueued.  Earlier sub-transactions may have
            # committed (best-effort batch) — the re-scheduled tasks then
            # hit the status-unchanged early return or node-version check.
            log.exception("scheduler tick transaction failed")
            failed.extend(successful)
            return [], failed
        finally:
            # always release no-longer-used volumes (reference: defer at
            # scheduler.go:501)
            self.store.batch(self.volumes.free_volumes)

    def _task_fit_node(self, t: Task, node_id: str) -> Optional[Task]:
        """Validate a preassigned task against its node
        (reference: scheduler.go:646)."""
        info = self.node_set.node_info(node_id)
        if info is None:
            return None
        self.pipeline.set_task(t)
        if not self.pipeline.process(info):
            new_t = t.copy()
            new_t.status.timestamp = now()
            new_t.status.err = self.pipeline.explain()
            self.all_tasks[t.id] = new_t
            return new_t
        new_t = t.copy()
        try:
            attachments = self.volumes.choose_task_volumes(t, info)
        except ValueError as e:
            new_t.status.timestamp = now()
            new_t.status.err = str(e)
            self.all_tasks[t.id] = new_t
            return new_t
        new_t.volumes = attachments
        new_t.status = TaskStatus(
            state=TaskState.ASSIGNED, timestamp=now(),
            message="scheduler confirmed task can run on preassigned node")
        self.all_tasks[t.id] = new_t
        info.add_task(new_t)
        return new_t

    # --------------------------------------------------------- group schedule

    def _schedule_task_group(self, task_group: Dict[str, Task],
                             decisions: Dict[str, SchedulingDecision]) -> None:
        if self.batch_planner is not None:
            handled = self.batch_planner.schedule_group(
                self, task_group, decisions)
            if handled:
                if task_group:
                    self._no_suitable_node(
                        task_group, decisions,
                        explanation=getattr(self.batch_planner,
                                            "last_explanation", ""))
                return
        self._schedule_group_host(task_group, decisions)

    def _schedule_group_host(self, task_group: Dict[str, Task],
                             decisions: Dict[str, SchedulingDecision],
                             defer_leftover: bool = True) -> None:
        """The host oracle path: spread tree + sorted round-robin
        (reference: scheduler.go:694 scheduleTaskGroup).  Non-spread
        strategies route to their host oracle (scheduler/strategy.py) —
        bit-equal to the device strategy kernel, so breaker/fallback
        demotions never move a task; an UNKNOWN strategy name degrades
        to the spread tree and counts the strategy fallback."""
        t = next(iter(task_group.values()))
        sname = strategy_mod.strategy_of(t)
        if sname != strategy_mod.SPREAD:
            sinfo = strategy_mod.resolve(sname)
            if sinfo is not None:
                try:
                    with tracer.span("sched.strategy_host", "sched",
                                     tasks=len(task_group)):
                        strategy_mod.schedule_group_host(
                            self, task_group, decisions, sinfo)
                except Exception:
                    # a broken strategy (e.g. an unreadable learned-
                    # weights artifact) degrades to the spread tree —
                    # counted, never a failed tick
                    log.exception("strategy %s host oracle failed; "
                                  "spread path serves the group", sname)
                    strategy_mod.count_fallback(sname)
                else:
                    if task_group and defer_leftover:
                        self._no_suitable_node(task_group, decisions)
                    return
            else:
                strategy_mod.count_fallback(sname)
        self.pipeline.set_task(t)
        ts = now()

        def node_less(a: NodeInfo, b: NodeInfo) -> bool:
            fa = a.count_recent_failures(ts, t)
            fb = b.count_recent_failures(ts, t)
            if fa >= MAX_FAILURES or fb >= MAX_FAILURES:
                if fa > fb:
                    return False
                if fb > fa:
                    return True
            sa = a.active_tasks_count_by_service.get(t.service_id, 0)
            sb = b.active_tasks_count_by_service.get(t.service_id, 0)
            if sa != sb:
                return sa < sb
            return a.active_tasks_count < b.active_tasks_count

        prefs = t.spec.placement.preferences if t.spec.placement else []
        with tracer.span("sched.host_fallback", "sched",
                         tasks=len(task_group)):
            tree = self.node_set.tree(t.service_id, prefs, len(task_group),
                                      self.pipeline.process, node_less)
            self._schedule_n_tasks_on_subtree(len(task_group), task_group,
                                              tree, decisions, node_less)
        if task_group and defer_leftover:
            # gang scratch placement (defer_leftover=False) leaves the
            # shortfall in task_group for the caller's atomic rollback
            self._no_suitable_node(task_group, decisions)

    def _schedule_n_tasks_on_subtree(self, n: int,
                                     task_group: Dict[str, Task],
                                     tree: DecisionTree,
                                     decisions: Dict[str, SchedulingDecision],
                                     node_less) -> int:
        """Recursive branch equalization (reference: scheduler.go:772)."""
        if tree.next is None:
            nodes = tree.ordered_nodes(self.pipeline.process)
            if not nodes:
                return 0
            return self._schedule_n_tasks_on_nodes(n, task_group, nodes,
                                                   decisions, node_less)

        tasks_scheduled = 0
        tasks_in_usable_branches = tree.tasks
        no_room: set = set()

        converging = True
        while (tasks_scheduled != n and len(no_room) != len(tree.next)
               and converging):
            usable = len(tree.next) - len(no_room)
            desired, remainder = divmod(
                tasks_in_usable_branches + n - tasks_scheduled, usable)
            converging = False
            for subtree in tree.next.values():
                if id(subtree) in no_room:
                    continue
                subtree_tasks = subtree.tasks
                if (subtree_tasks < desired
                        or (subtree_tasks == desired and remainder > 0)):
                    converging = True
                    to_assign = desired - subtree_tasks
                    if remainder > 0:
                        to_assign += 1
                    res = self._schedule_n_tasks_on_subtree(
                        to_assign, task_group, subtree, decisions, node_less)
                    if res < to_assign:
                        no_room.add(id(subtree))
                        tasks_in_usable_branches -= subtree_tasks
                    elif remainder > 0:
                        remainder -= 1
                    tasks_scheduled += res
        return tasks_scheduled

    def _schedule_n_tasks_on_nodes(self, n: int,
                                   task_group: Dict[str, Task],
                                   nodes: List[NodeInfo],
                                   decisions: Dict[str, SchedulingDecision],
                                   node_less) -> int:
        """Round-robin assignment over sorted candidates, re-filtering the
        mutated node after each placement (reference: scheduler.go:844)."""
        tasks_scheduled = 0
        failed_constraints: Dict[int, bool] = {}
        node_iter = 0
        node_count = len(nodes)
        for task_id, t in list(task_group.items()):
            if task_id in decisions:
                continue
            node = nodes[node_iter % node_count]
            try:
                attachments = self.volumes.choose_task_volumes(t, node)
            except ValueError:
                attachments = []

            new_t = t.copy()
            new_t.volumes = attachments
            new_t.node_id = node.id
            self.volumes.reserve_task_volumes(new_t)
            new_t.status = TaskStatus(
                state=TaskState.ASSIGNED, timestamp=now(),
                message="scheduler assigned task to node")
            self.all_tasks[t.id] = new_t
            node.add_task(new_t)

            decisions[task_id] = SchedulingDecision(t, new_t)
            del task_group[task_id]
            tasks_scheduled += 1
            if tasks_scheduled == n:
                return tasks_scheduled

            if node_iter + 1 < node_count:
                # first pass: level nodes to equal task counts
                next_node = nodes[(node_iter + 1) % node_count]
                if node_less(next_node, node):
                    node_iter += 1
            else:
                node_iter += 1

            orig_iter = node_iter
            while (failed_constraints.get(node_iter % node_count)
                   or not self.pipeline.process(nodes[node_iter % node_count])):
                failed_constraints[node_iter % node_count] = True
                node_iter += 1
                if node_iter - orig_iter == node_count:
                    return tasks_scheduled
        return tasks_scheduled

    def _no_suitable_node(self, task_group: Dict[str, Task],
                          decisions: Dict[str, SchedulingDecision],
                          explanation: Optional[str] = None) -> None:
        if explanation is None:
            explanation = self.pipeline.explain()
        # one service lookup per group, not per task: all tasks in a group
        # share (service_id, spec_version)
        services: Dict[str, Optional[Service]] = {}
        for t in task_group.values():
            if t.service_id not in services:
                services[t.service_id] = self.store.raw_get(
                    Service, t.service_id)
            service = services[t.service_id]
            if service is None:
                continue
            new_t = t.copy()
            new_t.status.timestamp = now()
            sv = service.spec_version
            tv = new_t.spec_version
            if sv is not None and tv is not None and sv.index > tv.index:
                if (t.status.state == TaskState.PENDING
                        and t.desired_state >= TaskState.SHUTDOWN):
                    new_t.status.state = TaskState.SHUTDOWN
                    new_t.status.err = ""
            else:
                if explanation:
                    new_t.status.err = f"no suitable node ({explanation})"
                else:
                    new_t.status.err = "no suitable node"
                self._enqueue(new_t)
            self.all_tasks[t.id] = new_t
            decisions[t.id] = SchedulingDecision(t, new_t)
