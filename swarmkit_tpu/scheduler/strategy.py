"""Pluggable placement-scoring strategies: the host half of the seam.

The scorer used to be spread-only: the host oracle's ``node_less``
comparator (scheduler.py) and the device kernel's effective-level column
(ops/kernel.py ``plan_group``) both hard-coded the reference's
per-service-count spread semantics.  This module factors the *scoring
stage* into a registry of strategies that share everything else — the
bucket ladder, the feasibility masks, and the water-fill/pack-fill
placement primitives:

* ``spread``  (default): the reference semantics, untouched — spread
  groups keep riding the exact pre-seam code paths (tree walk on the
  host, ``plan_group``/``plan_fused`` on device), so placements are
  byte-identical to the pre-seam scheduler by construction.
* ``binpack``: least-free-capacity-first (capacity measured in units of
  the group's own demand).  Reduces stranded capacity under mixed-size
  replicas — the policy latent in the reference's scheduler design.
* ``weighted``: linear multi-criteria score over cpu/mem/generic
  headroom and the spread term, with per-service integer weights
  (PAPERS.md 0706.4009 multi-criteria scheduling).
* ``learned`` (experimental): a tiny fixed-weight integer MLP over
  per-node features, evaluated as just another vmap'd tasks×nodes
  kernel; weights load from a checked-in artifact trained offline
  against ``sim/scenario.py``-shaped traces (scripts/train_scorer.py;
  GFlowNet-style robust scheduling is the stretch goal, PAPERS.md
  2302.05446).

Every non-spread strategy has BOTH a host oracle (this module — pure
numpy, exact integer math) and a device kernel
(``ops/kernel.plan_strategy``).  The two consume identical integer
columns and apply identical integer formulas, so placements agree
bit-for-bit; the planner's breaker/fallback routing can therefore hand
any strategy group to the host oracle mid-tick without changing the
outcome.  All score arithmetic is integer (fixed-point for the MLP):
no float can round a host decision away from the device's.

Strategy is selected per service via the ``placement_strategy`` spec
field (``Placement.strategy``); weights ride ``strategy_weights``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, NamedTuple, Optional

import numpy as np

from ..models.objects import Task
from ..models.types import GenericResourceKind, PublishMode, now
from ..utils.metrics import registry as _metrics
from .nodeinfo import MAX_FAILURES, NodeInfo

# ---------------------------------------------------------------- constants
#
# Shared numeric envelope.  The first block MIRRORS ops/kernel.py (the
# kernel cannot be imported from here — ops imports scheduler, never the
# reverse); tests/test_strategy.py pins the pairs equal so they cannot
# drift.  The second block is canonical HERE and imported by the kernel.

K_CLAMP = 1 << 22          # mirrors ops.kernel.K_CLAMP
F_BIG = 1 << 22            # mirrors ops.kernel.F_BIG
FAILURE_CLAMP = 63         # mirrors ops.kernel.FAILURE_CLAMP
SVC_CLAMP = (1 << 20) - 1  # mirrors ops.kernel.SVC_CLAMP
IDX_BITS = 20              # mirrors ops.kernel.IDX_BITS
TOTAL_CLAMP = (1 << 10) - 1  # mirrors ops.kernel.TOTAL_CLAMP

#: weighted-strategy term weights clamp (ints; 0 disables a term)
W_CLAMP = 15
#: headroom columns clamp (units of the group's per-task demand)
HR_CLAMP = 1023
#: binpack freeness clamp: scores occupy 10 bits of the packed fill key
#: ([0, BP_CLAMP] capacity band + [BP_CLAMP+1, 1023] failure band), so
#: key = score << IDX_BITS | idx stays under 2^30 — the same search
#: range as the spread tie keys
BP_CLAMP = 959
#: learned-scorer output clamp (leaves room under the failure band)
SCORE_CLAMP = (1 << 24) - 1
#: MLP feature clamp (10-bit features keep int32 accumulators exact)
FEAT_CLAMP = 1023
#: fixed-point shift applied after each MLP layer
MLP_SHIFT = 7
#: MLP weight magnitude clamp (int8 envelope: F*FEAT_CLAMP*127 < 2^31)
MLP_W_CLAMP = 127
#: feature order the artifact's w1 rows are trained against
MLP_FEATURES = ("svc", "total", "failures", "hr_cpu", "hr_mem", "ready")

SPREAD, BINPACK, WEIGHTED, LEARNED = \
    "spread", "binpack", "weighted", "learned"
STRAT_SPREAD, STRAT_BINPACK, STRAT_WEIGHTED, STRAT_LEARNED = 0, 1, 2, 3

#: weighted term order in the weights vector
WEIGHT_KEYS = ("spread", "cpu", "mem", "generic")


class StrategyInfo(NamedTuple):
    """One registered scoring strategy."""

    name: str
    sid: int                # static id the device kernel branches on
    uses_weights: bool      # ships the per-service weight vector
    uses_learned: bool      # ships the MLP parameter arrays


#: name -> StrategyInfo.  "" aliases spread (the unset spec default).
REGISTRY: Dict[str, StrategyInfo] = {}


def register(info: StrategyInfo) -> None:
    REGISTRY[info.name] = info


register(StrategyInfo(SPREAD, STRAT_SPREAD, False, False))
register(StrategyInfo(BINPACK, STRAT_BINPACK, False, False))
register(StrategyInfo(WEIGHTED, STRAT_WEIGHTED, True, False))
register(StrategyInfo(LEARNED, STRAT_LEARNED, False, True))


def strategy_of(t: Task) -> str:
    """The task's selected strategy name ("" normalizes to spread; an
    UNKNOWN name is returned verbatim — the scheduler serves it through
    the spread path and counts the fallback)."""
    p = t.spec.placement
    name = (p.strategy if p is not None else "") or SPREAD
    return name.lower()


def resolve(name: str) -> Optional[StrategyInfo]:
    return REGISTRY.get(name)


def count_fallback(name: str) -> None:
    """A non-spread strategy group was served by the spread path (the
    strategy could not be honored — unknown name).  The cfg11 bench
    gate pins this at 0 for spread/binpack workloads."""
    _metrics.counter(f'swarm_strategy_fallbacks{{strategy="{name}"}}')


def count_group(name: str, route: str) -> None:
    """Per-group routing counter: route is "device" (strategy kernel)
    or "host" (this module's oracle)."""
    _metrics.counter(
        f'swarm_strategy_groups{{route="{route}",strategy="{name}"}}')


def weights_of(t: Task) -> np.ndarray:
    """The weighted strategy's i32[4] term vector [spread, cpu, mem,
    generic], clamped to [0, W_CLAMP].  Unset/empty -> all ones, and a
    PARTIAL dict leaves the omitted terms at 1 too — writing
    {"cpu": 3} boosts cpu without silently disabling the spread term
    (a 0 must be explicit)."""
    p = t.spec.placement
    raw = (p.strategy_weights if p is not None else None) or {}
    out = np.ones(len(WEIGHT_KEYS), np.int32)
    for i, key in enumerate(WEIGHT_KEYS):
        if key not in raw:
            continue
        try:
            out[i] = min(max(int(raw[key]), 0), W_CLAMP)
        except (TypeError, ValueError):
            out[i] = 1
    return out


# ------------------------------------------------------- learned scorer

_LEARNED_PATH = os.path.join(os.path.dirname(__file__),
                             "learned_scorer.json")
_learned_cache: Optional[tuple] = None


def learned_params(path: Optional[str] = None) -> tuple:
    """The checked-in MLP artifact as (w1 i32[F,H], b1 i32[H],
    w2 i32[H], b2 i32[]) — fixed weights, loaded once, deterministic
    (NO randomness may enter here: a missing artifact is an error, not
    a random init — the determinism lint pins this).  Weights clamp to
    the int8 envelope so every accumulator below stays exact in
    int32."""
    global _learned_cache
    if path is None and _learned_cache is not None:
        return _learned_cache
    with open(path or _LEARNED_PATH) as f:
        doc = json.load(f)
    if doc.get("format") != "swarm-learned-scorer-v1":
        raise ValueError("unknown learned-scorer artifact format")
    if tuple(doc.get("features", ())) != MLP_FEATURES:
        raise ValueError("learned-scorer artifact feature order mismatch")
    if int(doc.get("shift", -1)) != MLP_SHIFT:
        raise ValueError("learned-scorer artifact shift mismatch")

    def arr(key, shape):
        a = np.clip(np.asarray(doc[key], np.int64),
                    -MLP_W_CLAMP, MLP_W_CLAMP).astype(np.int32)
        if a.shape != shape:
            raise ValueError(f"learned-scorer {key} shape {a.shape} != "
                             f"{shape}")
        return a

    hidden = int(doc["hidden"])
    f = len(MLP_FEATURES)
    params = (arr("w1", (f, hidden)), arr("b1", (hidden,)),
              arr("w2", (hidden,)), arr("b2", ()))
    if path is None:
        _learned_cache = params
    return params


def learned_features(svc, total, failures, hr_cpu, hr_mem,
                     ready) -> np.ndarray:
    """Per-node feature matrix i32[N, F] in MLP_FEATURES order, every
    column clamped into the 10-bit envelope.  The SAME formula runs on
    device (ops/kernel.py _learned_score) — integer, so bit-exact."""
    cols = (np.clip(svc, 0, FEAT_CLAMP),
            np.clip(total, 0, FEAT_CLAMP),
            np.clip(failures, 0, FEAT_CLAMP),
            np.clip(hr_cpu, 0, FEAT_CLAMP),
            np.clip(hr_mem, 0, FEAT_CLAMP),
            np.asarray(ready).astype(np.int32) * FEAT_CLAMP)
    return np.stack([np.asarray(c, np.int32) for c in cols], axis=-1)


def learned_score_host(features: np.ndarray, params: tuple) -> np.ndarray:
    """Fixed-point MLP forward pass, numpy.  h = relu((f·w1 + b1) >>
    SHIFT) clamped to the feature envelope; out = (h·w2 + b2) >> SHIFT
    clamped to [0, SCORE_CLAMP].  All int32, accumulators bounded by
    the clamps — exact, and identical to the device kernel."""
    w1, b1, w2, b2 = params
    f = features.astype(np.int32)
    h = np.right_shift(f @ w1 + b1, MLP_SHIFT)
    h = np.clip(h, 0, FEAT_CLAMP)
    out = np.right_shift(h @ w2 + b2, MLP_SHIFT)
    return np.clip(out, 0, SCORE_CLAMP).astype(np.int32)


# ------------------------------------------------------ scoring (host)

def failure_downweight(failures: np.ndarray) -> np.ndarray:
    """The spread kernel's failure penalty, shared verbatim by the
    waterfill strategies: nodes at/over MAX_FAILURES sink below every
    healthy node."""
    failures = np.asarray(failures, np.int64)
    return np.where(failures >= MAX_FAILURES,
                    np.clip(failures, 0, FAILURE_CLAMP), 0)


def binpack_key(res_cap, failures, idx) -> np.ndarray:
    """Packed fill-order key, lower = fill first: freeness (tasks of
    this group the node can still absorb, clamped to BP_CLAMP) in the
    top 10 bits, node index below; failure-heavy nodes ride the band
    above every healthy score."""
    res_cap = np.asarray(res_cap, np.int64)
    failures = np.asarray(failures, np.int64)
    score = np.where(failures >= MAX_FAILURES,
                     BP_CLAMP + 1 + np.clip(failures, 0, FAILURE_CLAMP),
                     np.clip(res_cap, 0, BP_CLAMP))
    return (score << IDX_BITS) | np.asarray(idx, np.int64)


def weighted_score(svc, hr_cpu, hr_mem, hr_gen, failures,
                   weights) -> np.ndarray:
    """Linear multi-criteria effective level, lower = preferred:
    spread term + inverted headroom terms (more headroom = lower
    score), failure penalty on top.  Bounded well under the 2^30
    water-level search range (15·2^20 + 3·15·1023 + 63·F_BIG)."""
    w = np.asarray(weights, np.int64)
    e = (w[0] * np.clip(np.asarray(svc, np.int64), 0, SVC_CLAMP)
         + w[1] * (HR_CLAMP - np.asarray(hr_cpu, np.int64))
         + w[2] * (HR_CLAMP - np.asarray(hr_mem, np.int64))
         + w[3] * (HR_CLAMP - np.asarray(hr_gen, np.int64))
         + failure_downweight(failures) * F_BIG)
    return e.astype(np.int32)


# -------------------------------------------- placement primitives (host)

def waterfill_host(e, cap, tie, k: int) -> np.ndarray:
    """Exact numpy mirror of ops/kernel.seg_waterfill (single segment):
    minimal level λ with fill(λ) >= k, base fill at λ-1, remainder
    granted to marginal nodes in tie order.  Device placements equal
    this bit-for-bit on equal inputs (the kernel's f32 segment sums are
    exact for every comparison that matters — see its docstring)."""
    e = np.asarray(e, np.int64)
    cap = np.asarray(cap, np.int64)
    tie = np.asarray(tie, np.int64)
    lo, hi = 0, 1 << 30
    while lo < hi:
        mid = (lo + hi) // 2
        if int(np.clip(mid - e, 0, cap).sum()) >= k:
            hi = mid
        else:
            lo = mid + 1
    lam = hi
    x = np.clip(lam - 1 - e, 0, cap)
    r = k - int(x.sum())
    if r > 0:
        marginal = (e <= lam - 1) & (x < cap)
        mt = np.sort(tie[marginal])
        if len(mt):
            thr = mt[min(r, len(mt)) - 1]
            x = x + (marginal & (tie <= thr)).astype(np.int64)
    return x.astype(np.int32)


def packfill_host(key, cap, k: int) -> np.ndarray:
    """Sequential fill in ascending key order (keys unique): each node
    takes its full capacity before the next starts — binpack.  Mirrors
    the kernel's threshold-search fill exactly."""
    key = np.asarray(key, np.int64)
    cap = np.asarray(cap, np.int64)
    order = np.argsort(key, kind="stable")
    c = cap[order]
    before = np.cumsum(c) - c
    x_o = np.clip(k - before, 0, c)
    x = np.zeros_like(cap)
    x[order] = x_o
    return x.astype(np.int32)


def plan_arrays_host(sid: int, k: int, cap, svc, total, failures,
                     hr_cpu, hr_mem, hr_gen, weights=None,
                     params=None, ready=None,
                     idx_offset: int = 0) -> np.ndarray:
    """The strategy seam's host oracle core: one group's per-node
    placement counts from densified integer columns.  ``cap`` is the
    EFFECTIVE capacity (feasibility-masked, k/maxrep/port-clamped —
    exactly what ops/kernel.feasibility_and_capacity emits); scores
    come from the strategy's formula above.  The device kernel
    (ops/kernel.plan_strategy) computes the same function."""
    n = len(cap)
    idx = np.arange(n, dtype=np.int64) + idx_offset
    kk = min(int(k), K_CLAMP)
    if sid == STRAT_WEIGHTED:
        e = weighted_score(svc, hr_cpu, hr_mem, hr_gen, failures,
                           weights if weights is not None
                           else np.ones(4, np.int32))
    elif sid == STRAT_LEARNED:
        feats = learned_features(svc, total, failures, hr_cpu, hr_mem,
                                 ready if ready is not None
                                 else np.ones(n, bool))
        score = learned_score_host(feats, params or learned_params())
        e = (score.astype(np.int64)
             + failure_downweight(failures) * F_BIG).astype(np.int32)
    else:
        raise ValueError(f"no host oracle for strategy id {sid}")
    tie = ((np.clip(np.asarray(total, np.int64), 0, TOTAL_CLAMP)
            << IDX_BITS) | idx)
    return waterfill_host(e, cap, tie, kk)


def plan_binpack_host(k: int, cap, res_cap, failures,
                      idx_offset: int = 0) -> np.ndarray:
    """Binpack host oracle: pack-fill by (freeness, index).  ``cap`` is
    the effective capacity, ``res_cap`` the raw absorbable count the
    freeness score reads (the kernel uses nodes.res_cap the same
    way)."""
    n = len(cap)
    idx = np.arange(n, dtype=np.int64) + idx_offset
    key = binpack_key(res_cap, failures, idx)
    return packfill_host(key, cap, min(int(k), K_CLAMP))


# ----------------------------------------- host column builders + entry

class HostColumns(NamedTuple):
    """Densified per-node integer columns for one group, built from the
    scheduler's NodeInfo mirror — the host twin of the planner's device
    inputs, sharing its formulas (exact int64 resource math)."""

    mask: np.ndarray      # bool[N] pipeline feasibility
    cap: np.ndarray       # i32[N] effective capacity
    res_cap: np.ndarray   # i32[N] raw absorbable count (binpack score)
    svc: np.ndarray       # i32[N]
    total: np.ndarray     # i32[N]
    failures: np.ndarray  # i32[N]
    hr_cpu: np.ndarray    # i32[N] headroom in demand units
    hr_mem: np.ndarray    # i32[N]
    hr_gen: np.ndarray    # i32[N]
    ready: np.ndarray     # bool[N]


def _headroom(avail: int, demand: int) -> int:
    if demand <= 0:
        return HR_CLAMP
    return int(min(max(avail // demand, 0), HR_CLAMP))


def build_host_columns(sched, t: Task, k: int,
                       infos: List[NodeInfo], ts: float) -> HostColumns:
    """One group's columns, mirroring ops/planner._build_device_inputs
    row formulas (res_cap = min over demanded resources of
    avail // demand in exact int64; effective cap additionally clamped
    by k, max_replicas and host-port exclusivity, zeroed off-mask)."""
    from ..models.types import NodeAvailability, NodeState

    n = len(infos)
    pipeline = sched.pipeline
    pipeline.set_task(t)
    mask = np.zeros(n, bool)
    ready = np.zeros(n, bool)
    res_cap = np.full(n, K_CLAMP, np.int64)
    svc = np.zeros(n, np.int32)
    total = np.zeros(n, np.int32)
    failures = np.zeros(n, np.int32)
    hr_cpu = np.zeros(n, np.int32)
    hr_mem = np.zeros(n, np.int32)
    hr_gen = np.zeros(n, np.int32)

    res = t.spec.resources.reservations if t.spec.resources else None
    cpu_d = int(res.nano_cpus) if res else 0
    mem_d = int(res.memory_bytes) if res else 0
    gen_wanted = [g for g in (res.generic if res else []) if g.value > 0]
    placement = t.spec.placement
    maxrep = placement.max_replicas if placement else 0
    port_limited = bool(t.endpoint and any(
        p.publish_mode == PublishMode.HOST and p.published_port
        for p in t.endpoint.ports))
    sid = t.service_id

    for i, info in enumerate(infos):
        node = info.node
        mask[i] = pipeline.process(info)
        ready[i] = (node.status.state == NodeState.READY
                    and node.spec.availability == NodeAvailability.ACTIVE)
        ar = info.available_resources
        cap_i = K_CLAMP
        if cpu_d > 0:
            cap_i = min(cap_i, int(ar.nano_cpus) // cpu_d)
        if mem_d > 0:
            cap_i = min(cap_i, int(ar.memory_bytes) // mem_d)
        gen_min = HR_CLAMP
        for g in gen_wanted:
            avail = 0
            for r in ar.generic:
                if r.kind == g.kind:
                    avail += (1 if r.res_type == GenericResourceKind.NAMED
                              else r.value)
            cap_i = min(cap_i, avail // g.value)
            gen_min = min(gen_min, _headroom(avail, g.value))
        res_cap[i] = cap_i
        svc[i] = info.active_tasks_count_by_service.get(sid, 0)
        total[i] = info.active_tasks_count
        if info.recent_failures:
            failures[i] = info.count_recent_failures(ts, t)
        hr_cpu[i] = _headroom(int(ar.nano_cpus), cpu_d)
        hr_mem[i] = _headroom(int(ar.memory_bytes), mem_d)
        hr_gen[i] = gen_min if gen_wanted else HR_CLAMP

    res_cap = np.clip(res_cap, 0, K_CLAMP).astype(np.int32)
    kk = min(int(k), K_CLAMP)
    cap = np.minimum(res_cap, kk)
    if maxrep > 0:
        cap = np.minimum(cap, np.maximum(maxrep - svc, 0))
    if port_limited:
        cap = np.minimum(cap, 1)
    cap = np.where(mask, np.maximum(cap, 0), 0).astype(np.int32)
    return HostColumns(mask, cap, res_cap, svc, total, failures,
                       hr_cpu, hr_mem, hr_gen, ready)


def plan_host(info: StrategyInfo, t: Task, cols: HostColumns,
              k: int) -> np.ndarray:
    """Placement counts for one group via ``info``'s host oracle."""
    if info.sid == STRAT_BINPACK:
        return plan_binpack_host(k, cols.cap, cols.res_cap,
                                 cols.failures)
    return plan_arrays_host(
        info.sid, k, cols.cap, cols.svc, cols.total, cols.failures,
        cols.hr_cpu, cols.hr_mem, cols.hr_gen,
        weights=weights_of(t) if info.uses_weights else None,
        params=learned_params() if info.uses_learned else None,
        ready=cols.ready)


def schedule_group_host(sched, task_group: Dict[str, Task], decisions,
                        info: StrategyInfo) -> None:
    """The scheduler's host path for a non-spread strategy group: build
    columns from the NodeInfo mirror, run the strategy's host oracle,
    and assign tasks with exactly the per-task mechanics of the spread
    tree path (volume choice, mirror add_task, decision rows).
    Leftover tasks stay in ``task_group`` for the caller's
    no-suitable-node pass."""
    from ..models.types import TaskState, TaskStatus
    from .scheduler import SchedulingDecision

    t = next(iter(task_group.values()))
    infos = list(sched.node_set.nodes.values())
    if not infos:
        return
    count_group(info.name, "host")
    ts = now()
    cols = build_host_columns(sched, t, len(task_group), infos, ts)
    x = plan_host(info, t, cols, len(task_group))
    slots = np.repeat(np.arange(len(infos)), x).tolist()
    items = list(task_group.items())
    placed = min(len(items), len(slots))
    for (task_id, task), i in zip(items[:placed], slots):
        node = infos[i]
        try:
            attachments = sched.volumes.choose_task_volumes(task, node)
        except ValueError:
            attachments = []
        new_t = task.copy()
        new_t.volumes = attachments
        new_t.node_id = node.id
        sched.volumes.reserve_task_volumes(new_t)
        new_t.status = TaskStatus(
            state=TaskState.ASSIGNED, timestamp=now(),
            message="scheduler assigned task to node")
        sched.all_tasks[task_id] = new_t
        node.add_task(new_t)
        decisions[task_id] = SchedulingDecision(task, new_t)
        del task_group[task_id]
