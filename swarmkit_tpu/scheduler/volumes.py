"""CSI volume scheduling: the scheduler also chooses volumes.

Reference: manager/scheduler/volumes.go, topology.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..models.objects import Task, Volume
from ..models.types import (
    Mount, MountType, VolumeAttachment, VolumeAvailability, VolumeSharing,
    VolumeAccessScope, VolumePublishStatus,
)
from .nodeinfo import NodeInfo

GROUP_PREFIX = "group:"


def is_in_topology(top: Optional[Dict[str, str]],
                   accessible: Sequence[Dict[str, str]]) -> bool:
    """True if node topology ``top`` lies within the volume's accessible
    topologies (reference: topology.go:22)."""
    if top is None or not accessible:
        return True
    for topology in accessible:
        if all(top.get(sub) == seg for sub, seg in topology.items()):
            return True
    return False


@dataclass
class _VolumeUsage:
    node_id: str
    read_only: bool


@dataclass
class _VolumeInfo:
    volume: Volume
    tasks: Dict[str, _VolumeUsage] = field(default_factory=dict)
    nodes: Dict[str, int] = field(default_factory=dict)  # node -> refcount


class VolumeSet:
    def __init__(self) -> None:
        self.volumes: Dict[str, _VolumeInfo] = {}
        self.by_group: Dict[str, set] = {}
        self.by_name: Dict[str, str] = {}
        self.frees_pending = False

    def clear(self) -> None:
        """Reset in place (holders of a reference — e.g. the pipeline's
        VolumesFilter — keep seeing the live set)."""
        self.volumes.clear()
        self.by_group.clear()
        self.by_name.clear()
        self.frees_pending = False

    def add_or_update_volume(self, v: Volume) -> None:
        info = self.volumes.get(v.id)
        if info is None:
            self.volumes[v.id] = _VolumeInfo(volume=v)
        else:
            info.volume = v
        self.by_group.setdefault(v.spec.group, set()).add(v.id)
        self.by_name[v.spec.annotations.name] = v.id

    def remove_volume(self, volume_id: str) -> None:
        info = self.volumes.pop(volume_id, None)
        if info is not None:
            self.by_group.get(info.volume.spec.group, set()).discard(volume_id)
            self.by_name.pop(info.volume.spec.annotations.name, None)

    # ------------------------------------------------------------ reservation

    def reserve_volume(self, volume_id: str, task_id: str, node_id: str,
                       read_only: bool) -> None:
        info = self.volumes.get(volume_id)
        if info is None:
            return
        info.tasks[task_id] = _VolumeUsage(node_id, read_only)
        info.nodes[node_id] = info.nodes.get(node_id, 0) + 1

    def release_volume(self, volume_id: str, task_id: str) -> None:
        info = self.volumes.get(volume_id)
        if info is None:
            return
        usage = info.tasks.pop(task_id, None)
        if usage is not None and info.nodes.get(usage.node_id, 0) > 0:
            info.nodes[usage.node_id] -= 1
            if info.nodes[usage.node_id] == 0:
                # a node just went unused: the next tick must run
                # free_volumes even if it commits no decisions
                self.frees_pending = True

    def reserve_task_volumes(self, task: Task) -> None:
        c = task.spec.container
        if c is None:
            return
        for va in task.volumes:
            for mount in c.mounts:
                if mount.source == va.source and mount.target == va.target:
                    self.reserve_volume(va.id, task.id, task.node_id,
                                        mount.readonly)

    # -------------------------------------------------------------- selection

    def choose_task_volumes(self, task: Task,
                            node_info: NodeInfo) -> List[VolumeAttachment]:
        """Pick concrete volumes for the task's CSI mounts on this node.

        Raises ValueError when a mount cannot be satisfied.  Reservations made
        while choosing are rolled back; the caller re-reserves on commit
        (reference: volumes.go:98 chooseTaskVolumes).
        """
        chosen: List[VolumeAttachment] = []
        try:
            c = task.spec.container
            if c is None:
                return []
            for mount in c.mounts:
                if mount.type != MountType.CSI:
                    continue
                candidate = self.is_volume_available_on_node(mount, node_info)
                if not candidate:
                    raise ValueError(
                        f"cannot find volume to satisfy mount with source "
                        f"{mount.source}")
                self.reserve_volume(candidate, task.id, node_info.id,
                                    mount.readonly)
                chosen.append(VolumeAttachment(
                    id=candidate, source=mount.source, target=mount.target))
            return chosen
        finally:
            for va in chosen:
                self.release_volume(va.id, task.id)

    def is_volume_available_on_node(self, mount: Mount,
                                    node: NodeInfo) -> str:
        source = mount.source
        if source.startswith(GROUP_PREFIX):
            group = source[len(GROUP_PREFIX):]
            for vid in self.by_group.get(group, ()):
                if self.check_volume(vid, node, mount.readonly):
                    return vid
            return ""
        vid = self.by_name.get(source, "")
        if vid and self.check_volume(vid, node, mount.readonly):
            return vid
        return ""

    def check_volume(self, volume_id: str, info: NodeInfo,
                     read_only: bool) -> bool:
        vi = self.volumes.get(volume_id)
        if vi is None:
            return False
        v = vi.volume
        if v.spec.availability != VolumeAvailability.ACTIVE:
            return False

        top: Optional[Dict[str, str]] = None
        if info.node.description:
            for csi in info.node.description.csi_info:
                if v.spec.driver and csi.plugin_name == v.spec.driver.name:
                    top = csi.accessible_topology
                    break

        if v.spec.access_mode.scope == VolumeAccessScope.SINGLE_NODE:
            for usage in vi.tasks.values():
                if usage.node_id != info.id:
                    return False

        sharing = v.spec.access_mode.sharing
        if sharing == VolumeSharing.NONE:
            if vi.tasks:
                return False
        elif sharing == VolumeSharing.ONEWRITER:
            if not read_only and any(not u.read_only
                                     for u in vi.tasks.values()):
                return False
        elif sharing == VolumeSharing.READONLY:
            if not read_only:
                return False

        accessible = (v.volume_info.accessible_topology
                      if v.volume_info else [])
        return is_in_topology(top, accessible)

    # ------------------------------------------------------------- unpublish

    def free_volumes(self, batch) -> None:
        """Queue PENDING_NODE_UNPUBLISH for volumes no longer used on a node
        (reference: volumes.go:186 freeVolumes)."""
        for volume_id, info in self.volumes.items():
            def cb(tx, volume_id=volume_id, info=info):
                v = tx.get(Volume, volume_id)
                if v is None:
                    return
                changed = False
                v = v.copy()
                for status in v.publish_status:
                    if (info.nodes.get(status.node_id, 0) == 0
                            and status.state == VolumePublishStatus.State.PUBLISHED):
                        status.state = \
                            VolumePublishStatus.State.PENDING_NODE_UNPUBLISH
                        changed = True
                if changed:
                    tx.update(v)
            batch.update(cb)
