from .ca import (
    CAServer, Certificate, InvalidCertificate, InvalidToken, KeyReadWriter,
    RootCA, SecurityError, generate_key_pem, make_csr,
)
from .tls import client_context, peer_certificate, server_context

__all__ = ["CAServer", "Certificate", "InvalidCertificate", "InvalidToken",
           "KeyReadWriter", "RootCA", "SecurityError", "generate_key_pem",
           "make_csr", "client_context", "peer_certificate",
           "server_context"]
