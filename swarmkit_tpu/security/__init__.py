from .ca import (
    CAServer, Certificate, InvalidCertificate, InvalidToken, KeyReadWriter,
    RootCA, SecurityError,
)

__all__ = ["CAServer", "Certificate", "InvalidCertificate", "InvalidToken",
           "KeyReadWriter", "RootCA", "SecurityError"]
