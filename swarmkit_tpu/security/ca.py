"""Cluster CA: node identity, join tokens, certificate issuance/rotation.

Reference: ca/{certificates.go,server.go,keyreadwriter.go} and
manager/encryption.

Scope note: the baked-in environment has no x509/TLS certificate library,
so certificates here are HMAC-signed identity attestations over the
cluster's root key — the full trust machinery (root CA material, join
tokens in the reference's SWMTKN format, role-gated issuance, renewal,
rotation with cross-trust, KEK-encrypted key storage) with the signature
primitive swapped.  A TLS transport can replace the primitive 1:1 at the
``RootCA.issue``/``verify`` seam.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..models.types import NodeRole

DEFAULT_NODE_CERT_EXPIRY = 90 * 24 * 3600.0  # reference: ca/certificates.go
TOKEN_VERSION = "SWMTKN-1"


class SecurityError(Exception):
    code = "unauthenticated"   # wire-error mapping (net/client.py)


class InvalidToken(SecurityError):
    pass


class InvalidCertificate(SecurityError):
    pass


def _b32(data: bytes) -> str:
    return base64.b32encode(data).decode("ascii").strip("=").lower()


@dataclass
class Certificate:
    """A signed node identity (role + expiry) — the mTLS cert stand-in."""

    node_id: str
    role: int
    issued_at: float
    expires_at: float
    issuer_digest: str
    signature: str = ""

    def payload(self) -> bytes:
        return json.dumps({
            "node_id": self.node_id, "role": self.role,
            "issued_at": self.issued_at, "expires_at": self.expires_at,
            "issuer": self.issuer_digest,
        }, sort_keys=True).encode()

    def to_bytes(self) -> bytes:
        return json.dumps({
            "node_id": self.node_id, "role": self.role,
            "issued_at": self.issued_at, "expires_at": self.expires_at,
            "issuer": self.issuer_digest, "sig": self.signature,
        }, sort_keys=True).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Certificate":
        try:
            d = json.loads(data)
            return cls(node_id=d["node_id"], role=d["role"],
                       issued_at=d["issued_at"],
                       expires_at=d["expires_at"],
                       issuer_digest=d["issuer"], signature=d["sig"])
        except Exception as e:
            raise InvalidCertificate(str(e))


class RootCA:
    """Cluster trust root (reference: ca/certificates.go:167 RootCA)."""

    def __init__(self, key: Optional[bytes] = None,
                 node_cert_expiry: float = DEFAULT_NODE_CERT_EXPIRY):
        self.key = key or os.urandom(32)
        self.node_cert_expiry = node_cert_expiry
        # secrets from which join tokens derive; rotating tokens replaces
        # these without touching the root key (reference: JoinTokens)
        self._token_secrets = {
            NodeRole.WORKER: os.urandom(16),
            NodeRole.MANAGER: os.urandom(16),
        }

    @property
    def digest(self) -> str:
        return hashlib.sha256(self.key).hexdigest()[:32]

    # ---------------------------------------------------------- join tokens

    def join_token(self, role: NodeRole) -> str:
        """reference token shape: SWMTKN-1-<root digest>-<role secret>."""
        return "-".join([
            TOKEN_VERSION, self.digest,
            _b32(self._token_secrets[NodeRole(role)])])

    def restore_join_tokens(self, join_tokens) -> None:
        """Adopt previously issued tokens (cluster restart): the role
        secrets are recovered from the stored token strings."""
        for role, token in ((NodeRole.WORKER, join_tokens.worker),
                            (NodeRole.MANAGER, join_tokens.manager)):
            if not token:
                continue
            parts = token.split("-")
            if len(parts) != 4:
                continue
            pad = "=" * (-len(parts[3]) % 8)
            try:
                self._token_secrets[role] = base64.b32decode(
                    parts[3].upper() + pad)
            except Exception:
                pass

    def rotate_join_token(self, role: NodeRole) -> str:
        self._token_secrets[NodeRole(role)] = os.urandom(16)
        return self.join_token(role)

    def role_for_token(self, token: str) -> NodeRole:
        parts = token.split("-")
        if len(parts) != 4 or parts[0] + "-" + parts[1] != TOKEN_VERSION:
            raise InvalidToken("invalid join token")
        if parts[2] != self.digest:
            raise InvalidToken("join token is for a different cluster")
        for role, secret in self._token_secrets.items():
            if hmac.compare_digest(parts[3], _b32(secret)):
                return role
        raise InvalidToken("invalid join token")

    # --------------------------------------------------------- certificates

    def issue(self, node_id: str, role: int,
              expiry: Optional[float] = None) -> Certificate:
        """reference: ca/server.go:234 IssueNodeCertificate +
        signNodeCert :764."""
        now = time.time()
        cert = Certificate(
            node_id=node_id, role=int(role), issued_at=now,
            expires_at=now + (expiry or self.node_cert_expiry),
            issuer_digest=self.digest)
        cert.signature = hmac.new(self.key, cert.payload(),
                                  hashlib.sha256).hexdigest()
        return cert

    def verify(self, cert: Certificate) -> None:
        if cert.issuer_digest != self.digest:
            raise InvalidCertificate("certificate from unknown issuer")
        expect = hmac.new(self.key, cert.payload(),
                          hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, cert.signature):
            raise InvalidCertificate("bad certificate signature")
        if cert.expires_at < time.time():
            raise InvalidCertificate("certificate expired")

    def needs_renewal(self, cert: Certificate,
                      threshold: float = 0.5) -> bool:
        """Renew past half of validity (the reference renews in a jittered
        window before expiry, ca/renewer.go)."""
        lifetime = cert.expires_at - cert.issued_at
        return time.time() > cert.issued_at + lifetime * threshold


class KeyReadWriter:
    """Node key-material persistence with a KEK encryption seam
    (reference: ca/keyreadwriter.go; encryption: manager/encryption)."""

    def __init__(self, path: str, kek: Optional[bytes] = None):
        self.path = path
        self.kek = kek

    def _stream(self, data: bytes, key: bytes) -> bytes:
        # XOR keystream from SHA256(kek || counter): stdlib-only symmetric
        # encryption stand-in behind the same seam nacl/fernet fill in the
        # reference
        out = bytearray()
        counter = 0
        while len(out) < len(data):
            block = hashlib.sha256(
                key + counter.to_bytes(8, "big")).digest()
            out.extend(block)
            counter += 1
        return bytes(a ^ b for a, b in zip(data, out[:len(data)]))

    def write(self, cert: Certificate, ca_key: bytes) -> None:
        payload = json.dumps({
            "cert": cert.to_bytes().decode(),
            "key": base64.b64encode(ca_key).decode(),
        }).encode()
        if self.kek:
            payload = b"ENC1" + self._stream(payload, self.kek)
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self.path)

    def read(self) -> Tuple[Certificate, bytes]:
        with open(self.path, "rb") as f:
            payload = f.read()
        if payload.startswith(b"ENC1"):
            if not self.kek:
                raise SecurityError("key material is locked (no KEK)")
            payload = self._stream(payload[4:], self.kek)
        try:
            d = json.loads(payload)
        except Exception:
            raise SecurityError("key material is corrupt or KEK is wrong")
        return (Certificate.from_bytes(d["cert"].encode()),
                base64.b64decode(d["key"]))

    def rotate_kek(self, new_kek: Optional[bytes]) -> None:
        cert, key = self.read()
        self.kek = new_kek
        self.write(cert, key)


class CAServer:
    """Issues certificates to token-bearing joiners
    (reference: ca/server.go:420 Run / :234 IssueNodeCertificate)."""

    def __init__(self, root_ca: RootCA):
        self.root_ca = root_ca

    def issue_node_certificate(self, node_id: str,
                               token: str) -> Certificate:
        role = self.root_ca.role_for_token(token)
        return self.root_ca.issue(node_id, role)

    def renew(self, cert: Certificate) -> Certificate:
        self.root_ca.verify(cert)
        return self.root_ca.issue(cert.node_id, cert.role)
