"""Cluster CA: real x509 node identity, join tokens, issuance/rotation.

Reference: ca/{certificates.go,server.go,keyreadwriter.go} and
manager/encryption.

Certificates are real x509 (EC P-256, ECDSA-SHA256) built with the
``cryptography`` library, mirroring the reference's layout
(ca/certificates.go:167 RootCA; signNodeCert server.go:764):

  - root: self-signed CA cert, 20y validity, CN=swarm-ca, O=<cluster id>
  - node: CN=<node id>, OU=<role: swarm-manager|swarm-worker>,
    O=<cluster id>, signed by the root, default 90d validity

The same PEM material feeds the TLS transports (security/tls.py); the
``Certificate`` dataclass carries the cert PEM (wire form) plus the
private key and trust-root PEM locally (never serialized).  Join tokens
follow the reference's SWMTKN-1-<root cert digest>-<role secret> shape,
so a joiner can bootstrap-verify the downloaded root against its token
(reference: ca.DownloadRootCA digest check).
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

log = logging.getLogger("security.ca")

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # gated dependency: importable module, unusable CA
    HAVE_CRYPTOGRAPHY = False

    class _MissingCrypto:
        """Raises on first use so importing this module (and everything
        that transitively pulls it in: manager, swarmd, agent wiring)
        works without the ``cryptography`` package; only actually
        creating/parsing certificates requires it."""

        def __init__(self, name: str):
            self._name = name

        def __getattr__(self, attr: str):
            raise ImportError(
                f"the 'cryptography' package is required for "
                f"{self._name}.{attr} (CA/TLS certificate operations)")

        def __call__(self, *a, **kw):
            raise ImportError(
                "the 'cryptography' package is required for CA/TLS "
                "certificate operations")

    x509 = _MissingCrypto("x509")
    hashes = _MissingCrypto("hashes")
    serialization = _MissingCrypto("serialization")
    ec = _MissingCrypto("ec")
    NameOID = _MissingCrypto("NameOID")

from ..models.types import NodeRole

DEFAULT_NODE_CERT_EXPIRY = 90 * 24 * 3600.0  # reference: ca/certificates.go
ROOT_CA_EXPIRY = 20 * 365 * 24 * 3600.0
TOKEN_VERSION = "SWMTKN-1"

# role <-> OU mapping (reference: ca/certificates.go ManagerRole/WorkerRole)
ROLE_OU = {NodeRole.MANAGER: "swarm-manager", NodeRole.WORKER: "swarm-worker"}
OU_ROLE = {v: k for k, v in ROLE_OU.items()}


class SecurityError(Exception):
    code = "unauthenticated"   # wire-error mapping (net/client.py)


class InvalidToken(SecurityError):
    pass


class InvalidCertificate(SecurityError):
    pass


def _b32(data: bytes) -> str:
    return base64.b32encode(data).decode("ascii").strip("=").lower()


def _ts(dt: datetime.datetime) -> float:
    return dt.replace(tzinfo=datetime.timezone.utc).timestamp() \
        if dt.tzinfo is None else dt.timestamp()


def _utc(ts: float) -> datetime.datetime:
    return datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)


def cert_digest(cert_pem: bytes) -> str:
    """Digest of a certificate's DER bytes — the token-embedded root
    fingerprint (must match RootCA.digest; both sides call this)."""
    der = x509.load_pem_x509_certificate(cert_pem).public_bytes(
        serialization.Encoding.DER)
    return hashlib.sha256(der).hexdigest()[:32]


def split_pem_certs(pem: bytes) -> list:
    """Individual certificate PEM blocks from a bundle."""
    marker = b"-----BEGIN CERTIFICATE-----"
    return [marker + part.split(b"-----END CERTIFICATE-----")[0]
            + b"-----END CERTIFICATE-----\n"
            for part in pem.split(marker)[1:]]


def signing_root_digest(cert: "Certificate") -> str:
    """Digest of the root (within the cert's own trust bundle) that
    signed its leaf — how a node tells whether its identity chains to
    the root a manager currently advertises ('' when undetermined)."""
    try:
        parsed = cert._x509()
    except Exception:
        return ""
    for ca_pem in split_pem_certs(cert.ca_cert_pem):
        try:
            ca = x509.load_pem_x509_certificate(ca_pem)
            if parsed.issuer != ca.subject:
                continue
            ca.public_key().verify(
                parsed.signature, parsed.tbs_certificate_bytes,
                ec.ECDSA(parsed.signature_hash_algorithm))
            return cert_digest(ca_pem)
        except Exception:
            continue
    return ""


def generate_key_pem() -> bytes:
    key = ec.generate_private_key(ec.SECP256R1())
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def make_csr(node_id: str, key_pem: bytes) -> bytes:
    """Client-side CSR for network issuance: the private key never leaves
    the node (reference: ca/certificates.go CreateCSR)."""
    key = serialization.load_pem_private_key(key_pem, password=None)
    csr = x509.CertificateSigningRequestBuilder().subject_name(
        x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, node_id)])
    ).sign(key, hashes.SHA256())
    return csr.public_bytes(serialization.Encoding.PEM)


@dataclass
class Certificate:
    """A node's x509 identity.  ``cert_pem`` is the wire form; the private
    key and the cluster trust root travel only inside the process / the
    node's key file."""

    cert_pem: bytes
    key_pem: bytes = b""       # node private key (local only)
    ca_cert_pem: bytes = b""   # trust root bundle (local only)

    def _x509(self) -> x509.Certificate:
        cached = self.__dict__.get("_parsed")
        if cached is None or self.__dict__.get("_parsed_src") != self.cert_pem:
            try:
                cached = x509.load_pem_x509_certificate(self.cert_pem)
            except Exception as e:
                raise InvalidCertificate(f"bad certificate PEM: {e}")
            self.__dict__["_parsed"] = cached
            self.__dict__["_parsed_src"] = self.cert_pem
        return cached

    @staticmethod
    def _name_attr(name: x509.Name, oid) -> str:
        attrs = name.get_attributes_for_oid(oid)
        return attrs[0].value if attrs else ""

    @property
    def node_id(self) -> str:
        return self._name_attr(self._x509().subject, NameOID.COMMON_NAME)

    @property
    def role(self) -> int:
        ou = self._name_attr(self._x509().subject,
                             NameOID.ORGANIZATIONAL_UNIT_NAME)
        return int(OU_ROLE.get(ou, NodeRole.WORKER))

    @property
    def org(self) -> str:
        return self._name_attr(self._x509().subject,
                               NameOID.ORGANIZATION_NAME)

    @property
    def issued_at(self) -> float:
        return _ts(self._x509().not_valid_before_utc)

    @property
    def expires_at(self) -> float:
        return _ts(self._x509().not_valid_after_utc)

    def to_bytes(self) -> bytes:
        return self.cert_pem

    @classmethod
    def from_bytes(cls, data: bytes) -> "Certificate":
        cert = cls(cert_pem=data)
        cert._x509()   # validate eagerly: wire data may be garbage
        return cert

    @classmethod
    def from_der(cls, der: bytes) -> "Certificate":
        try:
            parsed = x509.load_der_x509_certificate(der)
        except Exception as e:
            raise InvalidCertificate(f"bad certificate DER: {e}")
        return cls(cert_pem=parsed.public_bytes(serialization.Encoding.PEM))


class RootCA:
    """Cluster trust root (reference: ca/certificates.go:167 RootCA).

    ``key`` is the CA private key PEM — also used as the cluster's opaque
    secret for the WAL DEK and HMAC-transport fallback, matching the
    reference's use of the CA key material as the root of the key
    hierarchy (KEK -> DEK chain, manager/deks.go)."""

    def __init__(self, key: Optional[bytes] = None,
                 cert: Optional[bytes] = None,
                 node_cert_expiry: float = DEFAULT_NODE_CERT_EXPIRY):
        self.node_cert_expiry = node_cert_expiry
        if key is not None and not key.lstrip().startswith(b"-----"):
            raise ValueError(
                "RootCA key must be a private-key PEM (legacy raw-secret "
                "roots are not supported)")
        if key is None:
            key = generate_key_pem()
            cert = None
        self.key = key
        self._ca_key = serialization.load_pem_private_key(key, password=None)
        if cert is None:
            cert = self._self_sign()
        self.cert_pem = cert
        self._ca_cert = x509.load_pem_x509_certificate(cert)
        # secrets from which join tokens derive; rotating tokens replaces
        # these without touching the root key (reference: JoinTokens)
        self._token_secrets = {
            NodeRole.WORKER: os.urandom(16),
            NodeRole.MANAGER: os.urandom(16),
        }
        # in-progress root rotation (reference: api.RootRotation +
        # ca/reconciler.go): (new_key_pem, new_cert_pem, cross_signed_pem)
        self.rotation: Optional[Tuple[bytes, bytes, bytes]] = None

    @staticmethod
    def _self_sign_root(key, org: str) -> bytes:
        """Self-signed root with a SubjectKeyIdentifier — rotation keeps
        the subject name stable, so chains disambiguate issuers by key
        id, not name."""
        now = time.time()
        name = x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, "swarm-ca"),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        ])
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(_utc(now - 60))
                .not_valid_after(_utc(now + ROOT_CA_EXPIRY))
                .add_extension(x509.BasicConstraints(ca=True,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.SubjectKeyIdentifier.from_public_key(
                    key.public_key()), critical=False)
                .sign(key, hashes.SHA256()))
        return cert.public_bytes(serialization.Encoding.PEM)

    def _self_sign(self) -> bytes:
        org = _b32(os.urandom(10))   # cluster identity, baked into certs
        return self._self_sign_root(self._ca_key, org)

    def restore(self, key: bytes, cert: bytes) -> None:
        """Adopt persisted trust-root material (cluster restart)."""
        self.key = key
        self.cert_pem = cert
        self._ca_key = serialization.load_pem_private_key(key, password=None)
        self._ca_cert = x509.load_pem_x509_certificate(cert)

    # ----------------------------------------------------------- root rotation

    def cross_sign(self, new_cert_pem: bytes) -> bytes:
        """Old root signs a CA cert carrying the NEW root's subject and
        public key: nodes that trust only the old root then accept certs
        chaining through this intermediate (reference:
        ca/certificates.go CrossSignCACertificate)."""
        new_cert = x509.load_pem_x509_certificate(new_cert_pem)
        now_ts = time.time()
        cross = (x509.CertificateBuilder()
                 .subject_name(new_cert.subject)
                 .issuer_name(self._ca_cert.subject)
                 .public_key(new_cert.public_key())
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(_utc(now_ts - 60))
                 .not_valid_after(new_cert.not_valid_after_utc)
                 .add_extension(x509.BasicConstraints(ca=True,
                                                      path_length=None),
                                critical=True)
                 .add_extension(x509.SubjectKeyIdentifier.from_public_key(
                     new_cert.public_key()), critical=False)
                 .add_extension(
                     x509.AuthorityKeyIdentifier.from_issuer_public_key(
                         self._ca_cert.public_key()), critical=False)
                 .sign(self._ca_key, hashes.SHA256()))
        return cross.public_bytes(serialization.Encoding.PEM)

    def begin_rotation(self, new_key_pem: Optional[bytes] = None,
                       new_cert_pem: Optional[bytes] = None
                       ) -> Tuple[bytes, bytes, bytes]:
        """Start a root rotation: mint (or adopt) a new root and a
        cross-signed intermediate.  Issuance immediately switches to the
        new key; verification accepts both roots until finalize
        (reference: controlapi/ca_rotation.go newRootRotationObject)."""
        if new_key_pem is None:
            new_key_pem = generate_key_pem()
        if new_cert_pem is None:
            # same org (cluster identity), fresh root key + serial
            new_key = serialization.load_pem_private_key(new_key_pem,
                                                         password=None)
            new_cert_pem = self._self_sign_root(new_key, self.org)
        cross = self.cross_sign(new_cert_pem)
        self.rotation = (new_key_pem, new_cert_pem, cross)
        return self.rotation

    def restore_rotation(self, new_key_pem: bytes, new_cert_pem: bytes,
                         cross_pem: bytes) -> None:
        self.rotation = (new_key_pem, new_cert_pem, cross_pem)

    def finalize_rotation(self) -> None:
        """The new root becomes THE root; old-root certs stop verifying
        (the reconciler only finalizes once no node uses them)."""
        if self.rotation is None:
            return
        new_key, new_cert, _ = self.rotation
        self.rotation = None
        self.restore(new_key, new_cert)

    @property
    def active_digest(self) -> str:
        """Digest of the root nodes should be chaining to — the rotation
        target while one is in progress."""
        if self.rotation is not None:
            return cert_digest(self.rotation[1])
        return self.digest

    def trust_bundle(self) -> bytes:
        """PEM bundle clients should trust: both roots during rotation."""
        if self.rotation is not None:
            return self.cert_pem + self.rotation[1]
        return self.cert_pem

    def issuer_digest(self, cert: "Certificate") -> str:
        """Which root a node cert chains to ('' if neither)."""
        parsed = cert._x509()
        for ca_pem in ([self.cert_pem]
                       + ([self.rotation[1]] if self.rotation else [])):
            ca = x509.load_pem_x509_certificate(ca_pem)
            if parsed.issuer == ca.subject:
                try:
                    ca.public_key().verify(
                        parsed.signature, parsed.tbs_certificate_bytes,
                        ec.ECDSA(parsed.signature_hash_algorithm))
                    return cert_digest(ca_pem)
                except Exception:
                    continue
        return ""

    @property
    def org(self) -> str:
        attrs = self._ca_cert.subject.get_attributes_for_oid(
            NameOID.ORGANIZATION_NAME)
        return attrs[0].value if attrs else ""

    @property
    def digest(self) -> str:
        """Digest of the root certificate (token-embedded so joiners can
        verify a downloaded root, reference: ca/certificates.go digests)."""
        return cert_digest(self.cert_pem)

    # ---------------------------------------------------------- join tokens

    def join_token(self, role: NodeRole) -> str:
        """reference token shape: SWMTKN-1-<root digest>-<role secret>."""
        return "-".join([
            TOKEN_VERSION, self.digest,
            _b32(self._token_secrets[NodeRole(role)])])

    def restore_join_tokens(self, join_tokens) -> None:
        """Adopt previously issued tokens (cluster restart): the role
        secrets are recovered from the stored token strings."""
        for role, token in ((NodeRole.WORKER, join_tokens.worker),
                            (NodeRole.MANAGER, join_tokens.manager)):
            if not token:
                continue
            parts = token.split("-")
            if len(parts) != 4:
                continue
            pad = "=" * (-len(parts[3]) % 8)
            try:
                self._token_secrets[role] = base64.b32decode(
                    parts[3].upper() + pad)
            except Exception:
                pass

    def rotate_join_token(self, role: NodeRole) -> str:
        self._token_secrets[NodeRole(role)] = os.urandom(16)
        return self.join_token(role)

    def role_for_token(self, token: str) -> NodeRole:
        parts = token.split("-")
        if len(parts) != 4 or parts[0] + "-" + parts[1] != TOKEN_VERSION:
            raise InvalidToken("invalid join token")
        if parts[2] != self.digest:
            raise InvalidToken("join token is for a different cluster")
        for role, secret in self._token_secrets.items():
            if hmac.compare_digest(parts[3], _b32(secret)):
                return role
        raise InvalidToken("invalid join token")

    # --------------------------------------------------------- certificates

    def _build_cert(self, node_id: str, role: int, public_key,
                    expiry: Optional[float]) -> bytes:
        """Node cert under the active signer.  During a rotation the NEW
        key signs and the cross-signed intermediate travels appended in
        the PEM bundle, so peers trusting only the old root still verify
        the chain (reference: ca/certificates.go intermediates)."""
        now = time.time()
        signing_key, signing_cert, chain = self._ca_key, self._ca_cert, b""
        if self.rotation is not None:
            new_key_pem, new_cert_pem, cross = self.rotation
            signing_key = serialization.load_pem_private_key(
                new_key_pem, password=None)
            signing_cert = x509.load_pem_x509_certificate(new_cert_pem)
            chain = cross
        subject = x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, node_id),
            x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME,
                               ROLE_OU[NodeRole(role)]),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, self.org),
        ])
        cert = (x509.CertificateBuilder()
                .subject_name(subject)
                .issuer_name(signing_cert.subject)
                .public_key(public_key)
                .serial_number(x509.random_serial_number())
                .not_valid_before(_utc(now - 60))
                .not_valid_after(_utc(now + (expiry
                                             or self.node_cert_expiry)))
                .add_extension(x509.BasicConstraints(ca=False,
                                                     path_length=None),
                               critical=True)
                .add_extension(
                    x509.AuthorityKeyIdentifier.from_issuer_public_key(
                        signing_cert.public_key()), critical=False)
                .sign(signing_key, hashes.SHA256()))
        return cert.public_bytes(serialization.Encoding.PEM) + chain

    def issue(self, node_id: str, role: int,
              expiry: Optional[float] = None) -> Certificate:
        """In-process issuance: keypair generated here (reference:
        ca/server.go:234 IssueNodeCertificate + signNodeCert :764; network
        joiners instead send a CSR so their key never travels)."""
        key_pem = generate_key_pem()
        key = serialization.load_pem_private_key(key_pem, password=None)
        cert_pem = self._build_cert(node_id, role, key.public_key(), expiry)
        return Certificate(cert_pem=cert_pem, key_pem=key_pem,
                           ca_cert_pem=self.trust_bundle())

    def sign_csr(self, csr_pem: bytes, node_id: str, role: int,
                 expiry: Optional[float] = None) -> bytes:
        """Sign a joiner's CSR.  The CN/OU are chosen by the CA (from the
        validated token/identity), never trusted from the CSR subject."""
        try:
            csr = x509.load_pem_x509_csr(csr_pem)
        except Exception as e:
            raise InvalidCertificate(f"bad CSR: {e}")
        return self._build_cert(node_id, role, csr.public_key(), expiry)

    def verify(self, cert: Certificate) -> None:
        parsed = cert._x509()
        roots = [self._ca_cert]
        if self.rotation is not None:
            roots.append(x509.load_pem_x509_certificate(self.rotation[1]))
        ok = False
        for root in roots:
            if parsed.issuer != root.subject:
                continue
            try:
                root.public_key().verify(
                    parsed.signature, parsed.tbs_certificate_bytes,
                    ec.ECDSA(parsed.signature_hash_algorithm))
                ok = True
                break
            except Exception:
                continue
        if not ok:
            raise InvalidCertificate(
                "certificate does not chain to a cluster root")
        now = time.time()
        if _ts(parsed.not_valid_after_utc) < now:
            raise InvalidCertificate("certificate expired")
        if _ts(parsed.not_valid_before_utc) > now + 300:
            raise InvalidCertificate("certificate not yet valid")

    def needs_renewal(self, cert: Certificate,
                      threshold: float = 0.5) -> bool:
        return needs_renewal(cert, threshold)


def needs_renewal(cert: Certificate, threshold: float = 0.5) -> bool:
    """Renew past half of validity (the reference renews in a jittered
    window before expiry, ca/renewer.go).  Needs no CA material, so
    nodes can decide locally."""
    lifetime = cert.expires_at - cert.issued_at
    return time.time() > cert.issued_at + lifetime * threshold


class KeyReadWriter:
    """Node key-material persistence with a KEK encryption seam
    (reference: ca/keyreadwriter.go; encryption: manager/encryption).
    Sealed with the same nonce + encrypt-then-MAC construction the raft
    WAL uses (state/raft/storage.KeyEncoder) — a fixed-pad XOR would leak
    plaintext across rewrites and allow undetected tampering."""

    def __init__(self, path: str, kek: Optional[bytes] = None):
        self.path = path
        self.kek = kek

    def _encoder(self, kek: bytes):
        from ..state.raft.storage import KeyEncoder
        return KeyEncoder(kek)

    def write(self, cert: Certificate, ca_key: bytes) -> None:
        payload = json.dumps({
            "cert": cert.cert_pem.decode(),
            "node_key": cert.key_pem.decode(),
            "ca_cert": cert.ca_cert_pem.decode(),
            "key": base64.b64encode(ca_key).decode(),
        }).encode()
        if self.kek:
            payload = b"ENC2" + self._encoder(self.kek).encode(payload)
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self.path)

    def read(self) -> Tuple[Certificate, bytes]:
        with open(self.path, "rb") as f:
            payload = f.read()
        if payload.startswith(b"ENC2"):
            if not self.kek:
                raise SecurityError("key material is locked (no KEK)")
            from ..state.raft.storage import DecryptionError
            try:
                payload = self._encoder(self.kek).decode(payload[4:])
            except DecryptionError:
                raise SecurityError(
                    "key material is corrupt or KEK is wrong")
        try:
            d = json.loads(payload)
        except Exception:
            raise SecurityError("key material is corrupt or KEK is wrong")
        cert = Certificate(
            cert_pem=d["cert"].encode(),
            key_pem=d.get("node_key", "").encode(),
            ca_cert_pem=d.get("ca_cert", "").encode())
        return cert, base64.b64decode(d["key"])

    def rotate_kek(self, new_kek: Optional[bytes]) -> None:
        cert, key = self.read()
        self.kek = new_kek
        self.write(cert, key)


class CAServer:
    """Issues certificates to token-bearing joiners
    (reference: ca/server.go:420 Run / :234 IssueNodeCertificate).

    When ``external`` is set (ClusterSpec.ca_config.external_cas), CSR
    signing is delegated to the CFSSL-style endpoint(s) instead of the
    local root key (reference: ca/external.go); unreachable signers fall
    back to local signing with a warning (documented deviation —
    security/external.py)."""

    def __init__(self, root_ca: RootCA):
        self.root_ca = root_ca
        self.external = None   # security.external.ExternalCA when set

    def _sign(self, csr_pem: bytes, node_id: str, role: int) -> bytes:
        ext = self.external   # snapshot: the config daemon may swap it
        if ext is not None:
            from .external import ExternalSigningError
            try:
                pem = ext.sign_csr(csr_pem, node_id, role)
                self._check_external_cert(pem, csr_pem)
                return pem
            except ExternalSigningError as e:
                log.warning("external CA signing failed (%s); "
                            "falling back to local root", e)
        return self.root_ca.sign_csr(csr_pem, node_id, role)

    def _check_external_cert(self, cert_pem: bytes,
                             csr_pem: bytes) -> None:
        """A signer that 'succeeds' with a bad certificate must not
        poison node identity: the result has to parse, chain to the
        cluster root, and carry the CSR's public key — anything else is
        a signing failure (and engages the local fallback)."""
        from .external import ExternalSigningError
        try:
            cert = Certificate(cert_pem=cert_pem,
                               ca_cert_pem=self.root_ca.trust_bundle())
            self.root_ca.verify(cert)
            csr = x509.load_pem_x509_csr(csr_pem)
            cert_key = cert._x509().public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo)
            csr_key = csr.public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo)
            if cert_key != csr_key:
                raise ExternalSigningError(
                    "signer returned a certificate for a different key")
        except ExternalSigningError:
            raise
        except Exception as e:
            raise ExternalSigningError(
                f"signer returned an invalid certificate: {e}") from e

    def issue_node_certificate(self, node_id: str, token: str,
                               csr_pem: Optional[bytes] = None):
        """Token-gated issuance.  With a CSR (network join) returns the
        signed cert PEM; without (in-process) returns a full Certificate
        incl. a server-generated key."""
        role = self.root_ca.role_for_token(token)
        if csr_pem is not None:
            return self._sign(csr_pem, node_id, role)
        return self.root_ca.issue(node_id, role)

    def renew(self, cert: Certificate,
              csr_pem: Optional[bytes] = None,
              role: Optional[int] = None):
        """Cert-gated renewal: same identity, fresh validity.  ``role``
        overrides the cert's role — the caller passes the node's current
        role from the store, so a node promoted/demoted by the role
        manager picks up its new role on renewal (reference:
        ca/server.go:377 issues for the store's node.Role, which is how
        role changes reach the node)."""
        self.root_ca.verify(cert)
        if role is None:
            role = cert.role
        if csr_pem is not None:
            return self._sign(csr_pem, cert.node_id, role)
        return self.root_ca.issue(cert.node_id, role)
