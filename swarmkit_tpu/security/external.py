"""External CA: delegate node-certificate signing to a CFSSL-style
HTTP(S) endpoint instead of the local root key.

Reference: ca/external.go:1 (ExternalCA.Sign posting a CFSSL sign
request), ca/certificates.go request shape.  The operator configures
signer URLs in ClusterSpec.ca_config.external_cas; the manager then
POSTs each CSR as ``{"certificate_request": <pem>, "subject": {...}}``
to ``<url>`` and uses the returned certificate.

Deviation (documented): the reference can run managers that never hold
the root key at all; here the cluster root key stays with the managers
(it also seals the raft WAL), and the external signer is a signing
*policy*.  When every configured signer is unreachable the manager falls
back to local signing with a warning rather than refusing certs —
availability over purity; the fallback is visible in logs and counters.
"""

from __future__ import annotations

import json
import logging
import ssl
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

log = logging.getLogger("security.external")


class ExternalSigningError(Exception):
    """No configured external signer produced a certificate."""


# OU strings must match the local CA's role mapping (security/ca.py)
_ROLE_OU = {0: "swarm-worker", 1: "swarm-manager"}


class ExternalCA:
    """CFSSL-compatible signer client (reference: ca/external.go).

    ``urls``: signer endpoints, tried in order.  ``org``: the cluster id,
    carried in the subject override so the signer mints certs the
    cluster's authorization checks accept.  ``tls_identity``: optional
    manager Certificate for mutual TLS towards an https signer.
    ``ca_cert_pem``: trust anchor for verifying the signer's server cert.
    """

    def __init__(self, urls: Sequence[str], org: str = "",
                 tls_identity=None, ca_cert_pem: bytes = b"",
                 timeout: float = 5.0):
        self.urls: List[str] = [u for u in urls if u]
        self.org = org
        self.timeout = timeout
        self.stats = {"signed": 0, "errors": 0}
        self._ctx: Optional[ssl.SSLContext] = None
        if any(u.startswith("https") for u in self.urls):
            ctx = ssl.create_default_context()
            if ca_cert_pem:
                ctx.load_verify_locations(cadata=ca_cert_pem.decode())
                ctx.check_hostname = False
            if tls_identity is not None and tls_identity.key_pem:
                from .tls import _load_chain   # shared temp-file seam
                _load_chain(ctx, tls_identity.cert_pem,
                            tls_identity.key_pem)
            self._ctx = ctx

    def sign_csr(self, csr_pem: bytes, node_id: str, role: int) -> bytes:
        """POST the CSR to each signer until one returns a certificate
        (reference: external.go Sign + makeExternalSignRequest)."""
        payload = json.dumps({
            "certificate_request": csr_pem.decode(),
            "subject": {
                "CN": node_id,
                "names": [{"OU": _ROLE_OU.get(int(role), "swarm-worker"),
                           "O": self.org}],
            },
        }).encode()
        last: Optional[Exception] = None
        for url in self.urls:
            req = urllib.request.Request(
                url, data=payload,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout,
                        context=self._ctx if url.startswith("https")
                        else None) as resp:
                    body = json.loads(resp.read())
            except (urllib.error.URLError, OSError, ValueError) as e:
                log.warning("external CA %s failed: %s", url, e)
                self.stats["errors"] += 1
                last = e
                continue
            if not body.get("success", False):
                self.stats["errors"] += 1
                last = ExternalSigningError(str(body.get("errors")))
                continue
            cert = body.get("result", {}).get("certificate", "")
            if not cert:
                self.stats["errors"] += 1
                last = ExternalSigningError("signer returned no certificate")
                continue
            self.stats["signed"] += 1
            return cert.encode()
        raise ExternalSigningError(
            f"all external CAs failed (last: {last})")
