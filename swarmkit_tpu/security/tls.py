"""TLS transport security over the cluster CA.

Reference: ca/transport.go (NewServerTLSConfig / NewClientTLSConfig) —
every link in the reference runs mutual TLS rooted at the cluster CA.
Here the stdlib ``ssl`` module provides the handshake; certificates and
keys come from security/ca.py's x509 material.

Server contexts verify client certs against the cluster root when the
client presents one (CERT_OPTIONAL): the CA-issuance method must remain
reachable by certless token-bearing joiners on the same port, exactly
like the reference's NodeCA service; every other method is gated on the
TLS-authenticated peer identity by the server dispatch.

``ssl`` wants key material as files: contexts are built through a
private temp file that is unlinked immediately after loading.
"""

from __future__ import annotations

import os
import ssl
import tempfile
from typing import Optional

from .ca import Certificate, InvalidCertificate, SecurityError


def _load_chain(ctx: ssl.SSLContext, cert_pem: bytes,
                key_pem: bytes) -> None:
    fd, path = tempfile.mkstemp(prefix="swarm-tls-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(cert_pem + b"\n" + key_pem)
        ctx.load_cert_chain(path)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def server_context(identity: Certificate,
                   require_client_cert: bool = False) -> ssl.SSLContext:
    """mTLS server side: presents ``identity``, verifies client certs
    against the cluster root when offered (CERT_OPTIONAL — the issuance
    RPC is token-gated instead, like the reference's NodeCA).  Links that
    never serve joiners (raft peers) set ``require_client_cert``."""
    if not identity.key_pem or not identity.ca_cert_pem:
        raise SecurityError("server TLS identity needs key + trust root")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    _load_chain(ctx, identity.cert_pem, identity.key_pem)
    ctx.load_verify_locations(cadata=identity.ca_cert_pem.decode())
    ctx.verify_mode = (ssl.CERT_REQUIRED if require_client_cert
                       else ssl.CERT_OPTIONAL)
    return ctx


def client_context(identity: Optional[Certificate] = None,
                   ca_cert_pem: bytes = b"",
                   insecure: bool = False) -> ssl.SSLContext:
    """mTLS client side.  ``insecure=True`` skips server verification —
    only for the join bootstrap, where the fetched root is then checked
    against the token digest (reference: ca.DownloadRootCA)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False   # identity = cert CN (node id), not DNS
    if insecure:
        ctx.verify_mode = ssl.CERT_NONE
    else:
        ca = ca_cert_pem or (identity.ca_cert_pem if identity else b"")
        if not ca:
            raise SecurityError("client TLS needs the cluster root cert")
        ctx.load_verify_locations(cadata=ca.decode())
        ctx.verify_mode = ssl.CERT_REQUIRED
    if identity is not None and identity.key_pem:
        _load_chain(ctx, identity.cert_pem, identity.key_pem)
    return ctx


def peer_certificate(ssl_sock: ssl.SSLSocket) -> Optional[Certificate]:
    """The TLS-authenticated peer identity, or None when the peer sent no
    cert (certless joiner on a CERT_OPTIONAL server)."""
    der = ssl_sock.getpeercert(binary_form=True)
    if not der:
        return None
    return Certificate.from_der(der)


def require_server_role(ssl_sock: ssl.SSLSocket, role_ou: str) -> None:
    """Client-side authorization of the server: the chain is verified by
    the handshake, but only a manager-role cert may serve the cluster
    APIs (reference: ca/transport.go ServerName/role checks)."""
    cert = peer_certificate(ssl_sock)
    if cert is None:
        raise InvalidCertificate("server presented no certificate")
    from .ca import OU_ROLE, ROLE_OU
    from ..models.types import NodeRole
    ou = ROLE_OU.get(NodeRole(cert.role), "")
    if ou != role_ou:
        raise InvalidCertificate(
            f"server certificate role {ou!r} != required {role_ou!r}")
