"""Deterministic simulation & fault injection for the swarmkit-tpu
control plane.

FoundationDB-style testing: the whole cluster — raft consensus members,
the leader's scheduler + dispatcher, and worker agents — runs inside ONE
single-threaded event loop under a virtual clock and a seeded RNG.  Every
run is a pure function of its seed: the same seed produces a
byte-identical event trace, so any invariant violation the randomized
fuzzer finds replays exactly from its printed seed.

Layout:

* ``clock``       — virtual clock installed into models.types.now()
* ``engine``      — seeded event loop with trace recording
* ``faults``      — simulated network (drop/delay/duplicate/partition)
  and the fault-op vocabulary scenarios and the fuzzer share
* ``cluster``     — SimCluster: RaftCore members with in-memory WALs +
  a control plane (real Scheduler/Dispatcher driven synchronously) +
  sim agents
* ``invariants``  — safety checkers (single-leader-per-term, no
  committed-entry loss, FSM monotonicity, assignment safety, ...)
* ``scenario``    — named scenarios + the runner producing SimReport
* ``fuzz``        — randomized fault-schedule fuzzer over seed ranges

CLI::

    python -m swarmkit_tpu.sim --seed 7 --scenario partition-churn
    python -m swarmkit_tpu.sim --fuzz 50
"""

from .clock import VirtualClock
from .engine import SimEngine
from .faults import SimNetwork
from .invariants import InvariantViolation
from .scenario import SCENARIOS, SimReport, run_scenario
from .fuzz import fuzz

__all__ = [
    "VirtualClock", "SimEngine", "SimNetwork", "InvariantViolation",
    "SCENARIOS", "SimReport", "run_scenario", "fuzz",
]
