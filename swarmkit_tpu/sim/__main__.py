"""CLI for the deterministic simulator.

    python -m swarmkit_tpu.sim --seed 7 --scenario partition-churn
    python -m swarmkit_tpu.sim --seed 7 --scenario partition-churn --trace
    python -m swarmkit_tpu.sim --fuzz 50 [--start-seed 100]
    python -m swarmkit_tpu.sim --list

Exit status: 0 when every invariant held, 1 otherwise (failing seeds are
printed so they can be replayed verbatim).
"""

from __future__ import annotations

import argparse
import json
import sys

from .fuzz import failures, fuzz
from .scenario import SCENARIOS, run_scenario


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m swarmkit_tpu.sim")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--scenario", default=None,
                   choices=sorted(SCENARIOS),
                   help="scenario to run (single-run default: "
                        "partition-churn; fuzz-mode default: rotate "
                        "seeds through the whole registry pool)")
    p.add_argument("--fuzz", type=int, metavar="N", default=0,
                   help="run N seeds; without --scenario the seeds "
                        "rotate through every pooled scenario "
                        "(random-fuzz, failover, rolling-update chaos, "
                        "legacy raft_cp variants)")
    p.add_argument("--start-seed", type=int, default=0)
    p.add_argument("--managers", type=int, default=3)
    p.add_argument("--agents", type=int, default=5)
    p.add_argument("--trace", action="store_true",
                   help="dump the full event trace to stderr")
    p.add_argument("--trace-json", metavar="PATH", default=None,
                   help="write the run's Chrome trace-event JSON here "
                        "(fuzz mode: one file per seed, suffixed)")
    p.add_argument("--list", action="store_true",
                   help="list scenarios and exit")
    args = p.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            doc = (SCENARIOS[name].__doc__ or "").strip().split("\n")[0]
            print(f"{name:26s} {doc}")
        return 0

    if args.fuzz:
        def progress(r):
            mark = "ok" if r.ok else "FAIL"
            print(f"seed {r.seed:6d} {r.scenario:26s} {mark} "
                  f"trace={r.trace_hash[:12]} "
                  f"obs={r.obs_trace_sha256[:12]} events={r.events}",
                  file=sys.stderr)

        reports = fuzz(args.fuzz, start_seed=args.start_seed,
                       scenario=args.scenario, progress=progress)
        if args.trace_json:
            for r in reports:
                path = (args.trace_json if len(reports) == 1
                        else f"{args.trace_json}.seed{r.seed}")
                with open(path, "w") as f:
                    f.write(r.obs_trace)
        bad = failures(reports)
        print(json.dumps({
            "seeds": args.fuzz,
            "start_seed": args.start_seed,
            # per-seed identity: the engine trace hash AND the sha of the
            # Chrome span trace — both pure functions of the seed, so two
            # runs of the same command are byte-identical end to end
            "runs": [
                {"seed": r.seed, "scenario": r.scenario, "ok": r.ok,
                 "events": r.events, "trace_hash": r.trace_hash,
                 "obs_trace_sha256": r.obs_trace_sha256}
                for r in reports],
            "failures": [
                {"seed": r.seed, "scenario": r.scenario,
                 "violations": r.violations,
                 # the black box: spans/samples/store events/raft
                 # transitions around the violation, sha-stable per seed
                 "flightrec": r.flightrec_path,
                 "flightrec_sha256": r.flightrec_sha256,
                 "reproduce": f"python -m swarmkit_tpu.sim --seed "
                              f"{r.seed} --scenario {r.scenario}"}
                for r in bad],
            "ok": not bad,
        }, indent=2))
        return 1 if bad else 0

    report = run_scenario(args.scenario or "partition-churn", args.seed,
                          n_managers=args.managers, n_agents=args.agents,
                          keep_trace=args.trace)
    if args.trace:
        print("\n".join(report.trace), file=sys.stderr)
    if args.trace_json:
        with open(args.trace_json, "w") as f:
            f.write(report.obs_trace)
    print(json.dumps(report.to_dict(), indent=2))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
