"""Virtual time for deterministic simulation.

The control plane reads wall-clock time through one seam —
``models.types.now()`` — so installing a VirtualClock there puts every
timestamp, heartbeat TTL, debounce window, and orphan deadline under the
simulator's control.  Time only moves when the engine pops the next
event; nothing ever sleeps.
"""

from __future__ import annotations

from ..models import types as _types

# virtual epoch: an arbitrary but fixed "wall clock" origin so task
# timestamps look like real times in dumps and compare correctly
SIM_EPOCH = 1_700_000_000.0


class VirtualClock:
    def __init__(self, start: float = SIM_EPOCH):
        self._now = start
        self.start = start

    def time(self) -> float:
        return self._now

    def elapsed(self) -> float:
        return self._now - self.start

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(f"time went backwards: {t} < {self._now}")
        self._now = t

    def install(self) -> None:
        """Route models.types.now() through this clock."""
        _types.set_time_source(self.time)

    @staticmethod
    def uninstall() -> None:
        _types.set_time_source(None)

    def __enter__(self) -> "VirtualClock":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()
