"""SimCluster: an in-process multi-manager / multi-agent cluster driven
entirely by the simulation engine.

Two layers share one event loop, one virtual clock, and one seeded RNG:

* **Consensus layer** — N raft members built on the real ``RaftCore``
  (the same sans-IO state machine production uses) with an in-memory
  WAL that models durability faithfully: every Ready's hard state and
  entries persist BEFORE messages send, a crash loses all volatile
  state but keeps the WAL, and a crash-with-truncation loses the last
  k WAL records ("died before fsync").  Messages route through
  ``SimNetwork`` with seeded delay/drop/duplication and partitions.

* **Control-plane layer** — two modes share one agent/fault vocabulary:

  - *standalone* (``SimControlPlane``, the original subsystem shape):
    the real ``Scheduler`` and ``Dispatcher`` run single-threaded
    against one standalone leader store under virtual time while the
    consensus layer churns alongside; committed raft entries and store
    commits are invariant-checked independently.
  - *raft-attached* (``RaftControlPlane``, the failover scenarios):
    EVERY member owns a replicated ``MemoryStore`` fed from its raft
    log; the full control plane — scheduler, dispatcher, restart
    supervisor, replicated + global orchestrators — cold-starts on
    whichever member is the ready leader, writing through a
    member-bound ``SimRaftProposer`` (leadership-epoch fenced), and is
    torn down by the member's own role-transition handler the instant
    it is deposed.  Blocking on consensus pumps VIRTUAL time
    (re-entrant ``engine.run_until``), so agent traffic, elections and
    faults keep flowing while a control write is in flight.

Determinism contract: all object ids the simulation creates are
deterministic strings (``utils.identity.set_id_source`` is installed
for the run, so even orchestrator-created tasks get seeded ids), every
random draw comes from the engine's seeded RNG tree, and RaftCore
broadcasts iterate peers in sorted order — so a run's trace hash is a
pure function of (scenario, seed).
"""

from __future__ import annotations

import heapq
import json
import os
from typing import Callable, Dict, List, Optional

from ..manager.dispatcher import Config_ as DispatcherConfig, Dispatcher, \
    DispatcherError, ErrOverloaded
from ..models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    ReplicatedService, Resources, Service, ServiceMode, ServiceSpec, Task,
    TaskSpec, TaskState, TaskStatus, Version,
)
from ..models.types import TERMINAL_STATES, now
from ..scheduler import Scheduler
from ..scheduler.filters import VolumesFilter
from ..state.raft.core import (
    ENTRY_CONF, Entry, HardState, LEADER, RaftCore,
)
from ..state.raft.node import NotLeader, ProposalDropped, \
    ReadUnavailable, StaleEpoch
from ..state.store import MemoryStore
from ..utils.identity import set_id_source
from .engine import SimEngine
from .faults import NetConfig, SimNetwork
from .invariants import (
    GangInvariants, OverloadInvariants, PipelineInvariants,
    PreemptionInvariants, QosInvariants, RaftInvariants, ReadInvariants,
    TaskInvariants, UpdateInvariants, Violations,
    check_placement_quality, entry_digest,
)

#: entry-data prefix marking replicated control-plane store actions —
#: member stores apply (only) these; opaque workload payloads and the
#: standalone scenarios' store traffic are invisible to them
CP_MAGIC = b"cpstore:"

#: the failures the sim treats as "leadership/RPC fallout, retry later"
#: — enumerated (DispatcherError covers invalid/expired sessions,
#: NotLeader/StaleEpoch/ProposalDropped cover a deposal landing inside
#: a store write), NOT a blanket Exception: the simulator exists to
#: surface unexpected control-plane crashes, so anything else must
#: propagate and fail the scenario loudly.  Shared by the agents'
#: dispatcher RPCs and the control plane's own step/attach paths.
AGENT_RPC_ERRORS = (DispatcherError, NotLeader, ProposalDropped)


class SimManager:
    """One raft member with an in-memory durable WAL and (in
    raft-attached mode) a replicated control-plane store."""

    TICK = 0.1   # seconds of virtual time per raft tick

    def __init__(self, member_id: str, peers: List[str], engine: SimEngine,
                 net: SimNetwork, raft_inv: RaftInvariants,
                 with_store: bool = False):
        self.id = member_id
        self.peers = list(peers)
        self.engine = engine
        self.net = net
        self.raft_inv = raft_inv
        self.alive = True
        self.stopped = False
        self._tick_scale = 1.0   # clock-skew fault: >1 ticks slower
        # durable state ("disk"): survives crashes, lost records only
        # through explicit truncation faults
        self._wal_records: List[tuple] = []   # ("hs", HardState)|("ent", Entry)
        # apply taps for data entries: each is called (member_id, entry)
        # per applied non-conf entry and returns True when it consumed
        # the apply (ran the proposing store's commit callback) —
        # SimRaftProposer completes its waiters through this, mirroring
        # RaftNode._apply_entry's waiter handling.  Unconsumed CP_MAGIC
        # entries replay into the member's replicated store below.
        self.apply_taps: List[Callable[[str, Entry], bool]] = []
        # role-transition hooks (member, role, term) — the raft-attached
        # control plane detaches/fences through these; re-wired across
        # restarts because _new_core rebuilds the core object
        self.transition_hooks: List[Callable[["SimManager", str, int],
                                             None]] = []
        # replicated control-plane store (raft-attached mode): rebuilt
        # from the WAL on restart like a real manager's
        self.store: Optional[MemoryStore] = MemoryStore() if with_store \
            else None
        self._with_store = with_store
        # the member-bound proposer wired into self.store._proposer by
        # the control plane; kept here so restart() re-wires it into the
        # REBUILT store (a proposer-less rebuild would silently commit
        # post-restart writes locally, without consensus or fencing)
        self.store_proposer = None
        # entries whose store apply must wait: the store's update lock is
        # held by an in-flight local proposal (single thread), so remote
        # applies queue here and drain on the next tick after release
        self._deferred_entries: List[Entry] = []
        self.restarts = 0
        self.core = self._new_core()
        net.register(member_id, self._on_message)
        self._schedule_tick()

    @property
    def tick_scale(self) -> float:
        return self._tick_scale

    @tick_scale.setter
    def tick_scale(self, value: float) -> None:
        # clock-skew bookkeeping: while ANY member ticks off-rate, the
        # lease's "no election fits in this window" argument is void —
        # every core's lease_gate reads this registry
        self._tick_scale = value
        if value == 1.0:
            self.engine.clock_skew_members.discard(self.id)
        else:
            self.engine.clock_skew_members.add(self.id)

    def _new_core(self) -> RaftCore:
        core = RaftCore(self.id, self.peers, rng=self.engine.fork_rng(),
                        prevote=True)
        core.on_transition = self._on_transition
        # leader lease sized to one election timeout of VIRTUAL time
        # (TICK seconds per raft tick), drift margin shaved in the core;
        # auto-disabled while any clock-skew fault is live
        core.lease_duration = core.election_tick * self.TICK
        core.lease_gate = \
            lambda: not self.engine.clock_skew_members
        return core

    def _on_transition(self, member_id: str, role: str, term: int) -> None:
        # role transitions land in the flight recorder under virtual
        # time — part of the deterministic post-mortem a failing seed
        # dumps (scenario.run_scenario) — then fan out to control-plane
        # hooks (detach-and-fence on deposal)
        from ..obs.flightrec import flightrec
        flightrec.record_raft(member_id, role, term)
        for hook in list(self.transition_hooks):
            hook(self, role, term)

    # ------------------------------------------------------------ event loop

    def _schedule_tick(self) -> None:
        def loop():
            if self.stopped:
                return
            if self.alive:
                self._drain_deferred()
                self.core.tick()
                self.pump()
            self.engine.after(self.TICK * self.tick_scale,
                              f"{self.id} tick", loop)
        self.engine.after(self.TICK * self.tick_scale,
                          f"{self.id} tick", loop)

    def _on_message(self, msg) -> None:
        if not self.alive:
            return
        self.core.step(msg)
        self.pump()

    def pump(self) -> None:
        """The Ready loop: persist -> send -> apply -> advance, exactly
        the ordering RaftNode uses (durability before visibility)."""
        while self.core.has_ready():
            rd = self.core.ready()
            if rd.hard_state is not None:
                self._wal_records.append(
                    ("hs", HardState(rd.hard_state.term,
                                     rd.hard_state.voted_for,
                                     rd.hard_state.commit)))
            for e in rd.entries:
                self._wal_records.append(
                    ("ent", Entry(e.term, e.index, e.data, e.type)))
            for m in rd.messages:
                self.net.send(m)
            for e in rd.committed:
                self._apply(e)
            self.core.advance(rd)
        if self.core.role == LEADER:
            self.raft_inv.observe_leader(self.core.term, self.id)

    def _apply(self, e: Entry, replay: bool = False) -> None:
        self.raft_inv.observe_apply(self.id, e.index, e.term,
                                    f"{e.type}:{entry_digest(e.data)}")
        if e.type == ENTRY_CONF:
            try:
                change = json.loads(e.data)
                self.core.apply_conf_change(change["op"], change["id"])
            except Exception:
                pass
            return
        if not e.data:
            return
        consumed = False
        if not replay:
            # give proposers a chance to run the proposing store's commit
            # callback in the apply path (RaftNode._apply_entry parity);
            # a fenced/cancelled waiter leaves the entry unconsumed and
            # it replays into the member store like a remote entry
            for tap in list(self.apply_taps):
                if tap(self.id, e):
                    consumed = True
                    break
        if consumed or self.store is None \
                or not e.data.startswith(CP_MAGIC):
            return
        if not replay and (self._deferred_entries
                           or self.store._update_lock._lock.locked()):
            # the single thread is inside this store's own update (an
            # in-flight proposal pumping virtual time): applying now
            # would deadlock on the update lock.  Queue in log order;
            # the tick loop drains after the lock is released.
            self._deferred_entries.append(e)
            return
        self._apply_store_entry(e)

    def _apply_store_entry(self, e: Entry) -> None:
        from ..state import serde
        try:
            actions = serde.entry_to_actions(e.data[len(CP_MAGIC):])
            self.store.apply_store_actions(actions)
        except Exception as exc:
            # a member store that cannot apply a committed entry is
            # DIVERGED — that must fail the run loudly, not limp on
            self.raft_inv.v.record(
                "store-apply-failed",
                f"{self.id} failed to apply committed entry {e.index}: "
                f"{type(exc).__name__}: {exc}")

    def _drain_deferred(self) -> None:
        while self._deferred_entries \
                and not self.store._update_lock._lock.locked():
            self._apply_store_entry(self._deferred_entries.pop(0))

    # ---------------------------------------------------------------- faults

    def crash(self, truncate_wal: int = 0) -> None:
        """Lose all volatile state; optionally lose the last
        ``truncate_wal`` WAL records.

        Truncation models a crash BEFORE fsync — which is OUTSIDE raft's
        fault model: this member already acked those records, so the
        cluster may have counted it toward a commit majority.  Default
        scenarios and the fuzzer therefore crash with the WAL intact;
        truncation exists precisely so tests can inject a durability bug
        and prove the invariant checkers catch it (see
        tests/test_sim.py::test_checker_detects_seeded_durability_bug)."""
        if not self.alive:
            return
        self.alive = False
        # volatile state dies with the process: un-applied remote
        # entries will be re-applied from the WAL on restart
        self._deferred_entries.clear()
        if truncate_wal > 0:
            dropped = self._wal_records[-truncate_wal:]
            del self._wal_records[-truncate_wal:]
            self.engine.log(
                f"fault crash {self.id} truncate={len(dropped)}")
        else:
            self.engine.log(f"fault crash {self.id}")
        self.net.isolate(self.id)

    def restart(self) -> None:
        if self.alive:
            return
        self.restarts += 1
        hs, entries = self._replay_wal()
        self.core = self._new_core()
        self.core.load(hs, entries, None)
        if self._with_store:
            # rebuild the replicated store from the WAL, like a real
            # manager's bootstrap: replaying the committed prefix below
            # converges it bit-for-bit with the cluster's stores.  The
            # member-bound proposer carries over — if this member leads
            # again, its writes must ride consensus, fenced, as before.
            self.store = MemoryStore(proposer=self.store_proposer)
        # re-apply the committed prefix to the (new) state machine; the
        # invariant ledger cross-checks every re-applied entry
        for e in self.core.entries_from(1):
            if e.index > self.core.commit_index:
                break
            self._apply(e, replay=True)
            self.core.applied_index = e.index
        self.alive = True
        self.net.rejoin(self.id)
        self.engine.log(f"fault restart {self.id} "
                        f"commit={self.core.commit_index}")

    def _replay_wal(self):
        """Mirror RaftLogger._load_wal: later entry records override
        earlier ones at the same or higher index (truncation)."""
        hs = HardState()
        entries: List[Entry] = []
        for kind, rec in self._wal_records:
            if kind == "hs":
                hs = HardState(rec.term, rec.voted_for, rec.commit)
            else:
                while entries and entries[-1].index >= rec.index:
                    entries.pop()
                entries.append(rec)
        # a truncated WAL may report a commit index beyond the surviving
        # entries; clamp like a real bootstrap would (can't commit what
        # is not on disk)
        last = entries[-1].index if entries else 0
        if hs.commit > last:
            hs = HardState(hs.term, hs.voted_for, last)
        return hs, entries


class SimAgent:
    """A worker: registers with the dispatcher, heartbeats, advances the
    task FSM one step per cycle, fails tasks on command."""

    FSM_NEXT = {
        TaskState.ASSIGNED: TaskState.ACCEPTED,
        TaskState.ACCEPTED: TaskState.PREPARING,
        TaskState.PREPARING: TaskState.READY,
        TaskState.READY: TaskState.STARTING,
        TaskState.STARTING: TaskState.RUNNING,
    }

    def __init__(self, node_id: str, cp: "SimControlPlane",
                 interval: float = 1.0):
        self.node_id = node_id
        self.cp = cp
        self.engine = cp.engine
        self.interval = interval
        self.rate_scale = 1.0      # clock-skew fault
        self.alive = True
        self.partitioned = False
        self.fail_p = 0.0          # per-step chance of failing a RUNNING task
        self.session: Optional[str] = None
        # follower-served sessions (RaftControlPlane.follower_reads):
        # the member currently owning this agent's session, plus the
        # re-resolution backoff state (a failed member is avoided for a
        # jittered window instead of hammered)
        self._member_id: Optional[str] = None
        self._avoid: Dict[str, float] = {}
        self._fail_attempts = 0
        # thundering-herd spread: after a session failure the NEXT
        # re-registration waits out a seeded jittered window, so a
        # leader failover doesn't re-register the whole fleet inside
        # one heartbeat interval
        self._reg_defer_until = 0.0
        # admission-shed backoff: an ErrOverloaded status batch is
        # re-queued client-side (level-triggered re-derive) behind a
        # jittered window instead of hammering the saturated edge
        self._shed_attempts = 0
        self._send_defer_until = 0.0
        self._rng = cp.engine.fork_rng()
        self._schedule()

    def _schedule(self) -> None:
        def loop():
            if self.cp.stopped:
                return
            self.step()
            self.engine.after(self.interval * self.rate_scale,
                              f"agent {self.node_id} step", loop)
        # deterministic phase offset so agents don't step in lockstep
        self.engine.after(self._rng.random() * self.interval,
                          f"agent {self.node_id} step", loop)

    # ------------------------------------------------------------------ step

    def step(self) -> None:
        if not self.alive or self.partitioned:
            return
        cp = self.cp
        if getattr(cp, "follower_reads", False):
            self._step_follower(cp)
            return
        if getattr(cp, "busy", False):
            # a control-plane write is pumping virtual time through this
            # very event: touching the leader store now would deadlock
            # the single thread on its update lock.  Model it as RPC
            # backpressure — retry on the next agent step.
            return
        d = cp.dispatcher
        if d is None:
            return   # no leader control plane right now (failover gap)
        if self.session is None \
                and self.engine.clock.elapsed() < self._reg_defer_until:
            return   # spread re-registration phase after a failure
        drain = getattr(cp, "drain_deferred", None)
        if drain is not None:
            drain()   # never stage an RPC's write over a deferred backlog
        cp.busy = True
        try:
            if self.session is None:
                # the description carries the worker's resources: a
                # registration without them would zero the node's
                # capacity and starve reservation-carrying bands (the
                # preemption scenarios schedule against these numbers)
                self.session, _ = d.register(
                    self.node_id,
                    description=NodeDescription(
                        hostname=self.node_id,
                        resources=Resources(nano_cpus=8 * 10 ** 9,
                                            memory_bytes=32 << 30)))
                self.engine.log(f"agent {self.node_id} registered")
                self._fail_attempts = 0
            else:
                d.heartbeat(self.node_id, self.session)
            # keep using the dispatcher captured above: the register/
            # heartbeat pump may have deposed the leader mid-step, and
            # the cp.dispatcher property would now be None — a stopped
            # dispatcher raises DispatcherError, which is handled
            self._advance_tasks(d)
        except ErrOverloaded:
            # admission shed at the session edge: the session (if any)
            # is STILL VALID — back off and retry, don't re-register
            self._note_shed(None)
        except AGENT_RPC_ERRORS:
            # an RPC failure — invalid session, dispatcher stopping, a
            # proposal fenced by leadership loss — drops the session;
            # the agent re-registers with whoever leads next, behind a
            # seeded jittered window (thundering-herd spread)
            from ..remotes import backoff_with_jitter
            self.session = None
            self._reg_defer_until = self.engine.clock.elapsed() + \
                backoff_with_jitter(self._fail_attempts, self._rng,
                                    base=0.25)
            self._fail_attempts += 1
        finally:
            cp.busy = False

    def _note_shed(self, updates) -> None:
        """An ErrOverloaded from the dispatcher edge: the RPC was shed
        by admission control, NOT a session failure.  Record what the
        client observed (the overload invariants audit that every shed
        is dispatcher-counted and every shed task recovers), then back
        off behind the existing jittered-backoff seam — degraded is
        never silently lossy: ``_advance_tasks`` is level-triggered
        from committed rows, so the same updates re-derive and re-send
        once the window passes."""
        from ..remotes import backoff_with_jitter
        t = self.engine.clock.elapsed()
        delay = backoff_with_jitter(self._shed_attempts, self._rng,
                                    base=0.5)
        self._shed_attempts += 1
        self._send_defer_until = t + delay
        if self.session is None:
            # a shed REGISTRATION: hold the retry too
            self._reg_defer_until = t + delay
        inv = getattr(self.cp, "overload_inv", None)
        if inv is not None:
            inv.note_client_shed(self.node_id, updates)

    # --------------------------------------------- follower-served mode

    def _resolve_member(self) -> Optional["SimManager"]:
        """Session member by node-id hash over the member ring, skipping
        dead/avoided members and — when an alternative exists — the
        current leader: consumer sessions stay pinned to followers, off
        the coordinator.  Sticky: the current member is kept while it
        remains acceptable."""
        import zlib
        cp = self.cp
        members = cp.sim.managers
        t = self.engine.clock.elapsed()
        leader = cp.sim.leader()

        def ok(m, allow_leader):
            return (m.alive and m.store is not None
                    and self._avoid.get(m.id, 0.0) <= t
                    and (allow_leader or m is not leader))

        cur = next((m for m in members if m.id == self._member_id), None)
        if cur is not None and self.session is not None \
                and ok(cur, allow_leader=False):
            return cur
        start = zlib.crc32(self.node_id.encode()) % len(members)
        fallback = None
        for k in range(len(members)):
            m = members[(start + k) % len(members)]
            if ok(m, allow_leader=False):
                return m
            if fallback is None and ok(m, allow_leader=True):
                fallback = m
        return fallback    # only the leader (or nothing) is left

    def _step_follower(self, cp) -> None:
        """Follower-served session step: register/heartbeat against the
        sharded member's local dispatcher, read assignments from ITS
        replicated store, report status through it (the write forwards
        to the leader).  On any session failure, re-resolve to a
        DIFFERENT member with jittered backoff on the failed one."""
        from ..remotes import backoff_with_jitter, count_reconnect
        if cp.busy:
            return
        if self.session is None \
                and self.engine.clock.elapsed() < self._reg_defer_until:
            return   # spread re-registration phase after a failure
        member = self._resolve_member()
        if member is None:
            return
        d = cp.plane_for(member)
        if d is None:
            return
        cp.drain_deferred()
        cp.busy = True
        try:
            if self.session is None or self._member_id != member.id:
                if self.session is not None \
                        and self._member_id is not None:
                    # graceful handoff: release the old session so the
                    # previous member never TTL-expires us into DOWN
                    old = cp.plane_for_id(self._member_id)
                    if old is not None:
                        old.release_session(self.node_id, self.session)
                self.session, _ = d.register(
                    self.node_id,
                    description=NodeDescription(
                        hostname=self.node_id,
                        resources=Resources(nano_cpus=8 * 10 ** 9,
                                            memory_bytes=32 << 30)))
                cp.session_owner[self.node_id] = member.id
                self._member_id = member.id
                self._fail_attempts = 0
                self.engine.log(f"agent {self.node_id} registered "
                                f"on {member.id}")
            else:
                d.heartbeat(self.node_id, self.session)
            cp.count_read(member)
            self._advance_tasks(d, store=member.store)
        except ErrOverloaded:
            # admission shed: the session stays valid, the member stays
            # resolvable — back off, don't fail over
            self._note_shed(None)
        except AGENT_RPC_ERRORS:
            # session failover: avoid THIS member for a jittered window
            # so the re-register lands on a different one
            self.session = None
            if cp.session_owner.get(self.node_id) == member.id:
                cp.session_owner.pop(self.node_id, None)
            self._member_id = None
            self._avoid[member.id] = self.engine.clock.elapsed() + \
                backoff_with_jitter(self._fail_attempts, self._rng,
                                    base=0.5)
            self._fail_attempts += 1
            cp.read_stats["agent_reconnects"] += 1
            count_reconnect("session_invalid")
            self.engine.log(f"agent {self.node_id} session failover "
                            f"off {member.id}")
        finally:
            cp.busy = False

    def _advance_tasks(self, d=None, store=None) -> None:
        from ..state.store import ByNode
        if d is None:
            d = self.cp.dispatcher
            if d is None:
                return
        if store is None:
            store = self.cp.store
        if store is None:
            return
        tasks = store.view(
            lambda tx: tx.find(Task, ByNode(self.node_id)))
        updates = []
        for t in sorted(tasks, key=lambda t: t.id):
            state = TaskState(t.status.state)
            if state in TERMINAL_STATES:
                continue
            if t.desired_state >= TaskState.SHUTDOWN:
                updates.append((t.id, TaskStatus(
                    state=TaskState.SHUTDOWN, timestamp=now(),
                    message="sim shutdown")))
                continue
            if state == TaskState.RUNNING:
                if self.fail_p and self._rng.random() < self.fail_p:
                    updates.append((t.id, TaskStatus(
                        state=TaskState.FAILED, timestamp=now(),
                        message="sim fault", err="injected failure")))
                    self.engine.log(f"agent {self.node_id} failed task "
                                    f"{t.id}")
                elif t.desired_state == TaskState.COMPLETE:
                    # job task (jobs orchestrator): runs to completion
                    # one agent step after reaching RUNNING
                    updates.append((t.id, TaskStatus(
                        state=TaskState.COMPLETE, timestamp=now(),
                        message="sim job complete")))
                continue
            nxt = self.FSM_NEXT.get(state)
            if nxt is None or nxt > t.desired_state:
                # hold at the desired band: a rolling update stages its
                # replacement at desired READY until the old task stops
                # (the restart supervisor then flips desired to RUNNING)
                continue
            poison = getattr(self.cp, "poison_versions", None)
            if (poison and nxt == TaskState.RUNNING
                    and t.spec_version is not None
                    and t.spec_version.index in poison):
                # rollout-poison fault: tasks of a poisoned spec version
                # die on startup, deterministically — the update
                # supervisor's failure monitor must pause or roll back
                updates.append((t.id, TaskStatus(
                    state=TaskState.FAILED, timestamp=now(),
                    message="sim poison", err="injected version failure")))
                self.engine.log(f"fault rollout-poison {self.node_id} "
                                f"task {t.id}")
                continue
            poison_svc = getattr(self.cp, "poison_services", None)
            if (poison_svc and nxt == TaskState.RUNNING
                    and t.service_id in poison_svc):
                # stage-poison fault (pipeline-chaos): every task of the
                # marked service dies on startup — the pipeline
                # supervisor must observe the failures and halt the
                # downstream stages
                updates.append((t.id, TaskStatus(
                    state=TaskState.FAILED, timestamp=now(),
                    message="sim poison", err="injected stage failure")))
                self.engine.log(f"fault stage-poison {self.node_id} "
                                f"task {t.id}")
                continue
            updates.append((t.id, TaskStatus(
                state=nxt, timestamp=now(), message="sim")))
        if updates:
            if self.engine.clock.elapsed() < self._send_defer_until:
                return   # shed backoff window: re-derive next step
            try:
                d.update_task_status(self.node_id, self.session, updates)
                self._shed_attempts = 0
            except ErrOverloaded:
                # the edge shed this batch whole: session stays valid,
                # the level-triggered loop re-sends after the backoff
                self._note_shed(updates)
            except AGENT_RPC_ERRORS:
                self.session = None

    # ---------------------------------------------------------------- faults

    def crash(self) -> None:
        if self.alive:
            self.alive = False
            self.session = None
            self.engine.log(f"fault agent-crash {self.node_id}")

    def restart(self) -> None:
        if not self.alive:
            self.alive = True
            self.engine.log(f"fault agent-restart {self.node_id}")

    def partition(self, on: bool) -> None:
        self.partitioned = on
        self.engine.log(f"fault agent-partition {self.node_id} "
                        f"{'on' if on else 'off'}")


class _MuxAgent(SimAgent):
    """One multiplexed session: full ``SimAgent`` semantics — register,
    heartbeat, FSM advance, faults, follower failover — but NO private
    engine timer.  The owning :class:`MuxAgentFleet`'s shared wheel
    re-arms it after every step."""

    def __init__(self, node_id: str, cp, fleet: "MuxAgentFleet",
                 interval: float = 1.0):
        # set BEFORE super().__init__: the base constructor calls
        # _schedule(), which we route to the fleet's wheel
        self._fleet = fleet
        super().__init__(node_id, cp, interval=interval)

    def _schedule(self) -> None:
        # the same deterministic phase spread a solo agent gets, armed
        # on the shared wheel instead of a per-agent engine timer
        self._fleet._arm(self, self._rng.random() * self.interval)


class MuxAgentFleet:
    """The million-swarm harness (ISSUE 20 tentpole): thousands of
    dispatcher sessions multiplexed over ONE engine timer, one due-heap
    ("heartbeat wheel") and one per-tick RPC budget — the driver pops
    due sessions, steps each through the ordinary ``SimAgent`` path,
    and re-arms it at its own jittered cadence.  Sessions the budget
    could not serve stay due and drain on the next tick: client-side
    queueing IS the backpressure model, nothing is dropped.

    Seed-deterministic by construction: each session forks its own RNG
    from the engine tree (same draws as a solo agent), and the wheel
    orders ties by a monotone sequence number.

    Attach the fleet at scenario-setup time, BEFORE the run starts:
    the first leader's bootstrap creates worker Node records for every
    agent present on ``cp.agents`` at that moment.

    ``stats`` exposes the knobs the overload tests pin:

    * ``steps`` / ``driver_ticks`` — total sessions served / timer fires
    * ``max_due_backlog`` — peak count of due-but-unserved sessions
      right after a tick (the budget's queueing signal)
    * ``max_concurrent_registrations`` — peak registrations inside one
      driver tick; the thundering-herd test bounds this after a leader
      failover (the agents' seeded re-registration jitter spreads it)
    """

    def __init__(self, cp, n_sessions: int, interval: float = 1.0,
                 driver_interval: float = 0.25, rpc_budget: int = 256,
                 prefix: str = "f"):
        self.cp = cp
        self.engine = cp.engine
        self.interval = interval
        self.driver_interval = driver_interval
        self.rpc_budget = rpc_budget
        self._wheel: List[tuple] = []   # (due, seq, agent)
        self._seq = 0
        self.stats = {"steps": 0, "driver_ticks": 0,
                      "max_due_backlog": 0,
                      "max_concurrent_registrations": 0}
        self.agents: List[_MuxAgent] = [
            _MuxAgent(f"{prefix}{i}", cp, self, interval=interval)
            for i in range(n_sessions)]
        cp.agents.extend(self.agents)
        self.engine.every(driver_interval, "fleet driver", self._drive)

    def _arm(self, agent: SimAgent, delay: float) -> None:
        self._seq += 1
        heapq.heappush(self._wheel,
                       (self.engine.clock.elapsed() + delay,
                        self._seq, agent))

    def _drive(self):
        if self.cp.stopped:
            return False
        self.stats["driver_ticks"] += 1
        t = self.engine.clock.elapsed()
        budget = self.rpc_budget
        registrations = 0
        while self._wheel and self._wheel[0][0] <= t and budget > 0:
            _, _, a = heapq.heappop(self._wheel)
            budget -= 1
            had_session = a.session is not None
            a.step()
            self.stats["steps"] += 1
            if a.session is not None and not had_session:
                registrations += 1
            # stepping may have pumped virtual time (a store write on
            # this stack re-enters the engine); re-read the clock so the
            # re-arm lands relative to NOW, not the tick's start
            self._arm(a, a.interval * a.rate_scale)
        if registrations > self.stats["max_concurrent_registrations"]:
            self.stats["max_concurrent_registrations"] = registrations
        backlog = sum(1 for e in self._wheel if e[0] <= t)
        if backlog > self.stats["max_due_backlog"]:
            self.stats["max_due_backlog"] = backlog
        return None


class SimRaftProposer:
    """MemoryStore ``Proposer`` backed by the sim's consensus layer:
    proposals ride the real RaftCore through SimNetwork faults, and
    commit callbacks run in the proposing member's apply path (the
    ``SimManager.apply_taps`` seam), mirroring RaftNode's waiter
    handling.

    Two modes:

    * **unbound** (``member=None``) — routes each proposal to whichever
      member currently leads; the original shape the pipelined-commit
      scenario drives a standalone store with.
    * **member-bound** — the proposer IS one member's consensus
      identity (RaftNode parity): proposals are refused unless that
      member is the ready leader, every proposal carries the
      leadership epoch it was created under, entry data is tagged
      ``CP_MAGIC`` so every member's replicated store applies it, and
      the commit callback is fenced — a proposal whose epoch was
      fenced (deposal, explicit ``fence_epoch``) fails WITHOUT running
      its commit callback even when the entry itself commits (the
      member store then converges through the remote-apply path,
      exactly like RaftNode).  ``enforce_fencing=False`` disables the
      fence (checker-sensitivity tests): a stale commit then RUNS and
      the ``no-stale-epoch-commit`` invariant must catch it.

    Implements the async pair (``propose_async``/``wait_proposal``) the
    store's chunk-pipelined block commit uses, so leader churn against
    in-flight pipelined proposals is simulatable deterministically.
    ``wait_proposal`` advances VIRTUAL time by pumping the engine;
    ``engine.run_until`` is re-entrant, so this may be driven from
    inside engine events (control steps) as well as top-level code.
    """

    PUMP = 0.05      # virtual seconds per wait slice
    TIMEOUT = 30.0   # virtual seconds before a proposal is abandoned

    #: virtual seconds an unanswered read-index request waits before the
    #: barrier re-asks (the leader it targeted may be gone)
    READ_RETRY = 1.0

    def __init__(self, sim: "Sim", member: Optional[SimManager] = None,
                 violations: Optional[Violations] = None):
        self.sim = sim
        self.member = member
        self.violations = violations
        self.enforce_fencing = True
        #: checker-sensitivity seam: False serves linearizable reads
        #: WITHOUT the barrier — follower-reads-never-uncommitted must
        #: then catch the stale view
        self.enforce_read_barrier = True
        #: read-plane observer (ReadInvariants): judges every served view
        self.read_observer = None
        self._pending: Dict[tuple, dict] = {}
        self.stats = {"proposed": 0, "committed": 0, "dropped": 0,
                      "stale_epoch_rejects": 0}
        # one-shot "fault native-commit-plane store" coverage line (see
        # propose_async): logged when the first binary block entry rides
        # consensus with the native decode plane active
        self._native_cov_logged = False
        self.read_stats = {"reads": 0, "lease": 0, "read_index": 0,
                           "unavailable": 0}
        if member is not None:
            member.apply_taps.append(self._on_apply)
        else:
            for m in sim.managers:
                m.apply_taps.append(self._on_apply)

    # ------------------------------------------------------------- fencing

    @property
    def leadership_epoch(self) -> Optional[int]:
        """Fencing token for the store's epoch pinning (RaftNode
        parity); None in unbound mode (no fencing identity)."""
        if self.member is None:
            return None
        return self.member.core.leadership_epoch

    # ------------------------------------------------------------- proposer

    def propose_async(self, actions, commit_cb=None, epoch=None) -> dict:
        from ..state import serde
        if self.member is not None:
            target = self.member
            core = target.core
            if core.role != LEADER or not core.leader_ready \
                    or not target.alive:
                raise NotLeader(f"{target.id} is not a ready leader")
            cur = core.leadership_epoch
            if epoch is None:
                epoch = cur
            elif epoch != cur:
                # pre-serialization fence (RaftNode parity): the reign
                # this commit was planned under is over
                self.stats["stale_epoch_rejects"] += 1
                raise StaleEpoch(
                    f"{target.id}: proposal epoch {epoch} fenced "
                    f"(current {cur})")
        else:
            target = self.sim.leader()
            if target is None:
                raise RuntimeError("no ready raft leader to propose to")
        data = serde.actions_to_entry_data(actions)
        if data.startswith(serde.BLOCK_ENTRY_MAGIC) \
                and not self._native_cov_logged:
            # one-shot coverage line: the chaos sweep's fault-type x
            # component gate (scripts/chaos_sweep.py REQUIRED_CELLS)
            # requires the NATIVE columnar commit plane to have actually
            # carried a block through consensus — an empty cell means
            # the native path silently rotted out of the sweep
            self._native_cov_logged = True
            from .. import native
            if native.get_commit() is not None:
                self.sim.engine.log("fault native-commit-plane store")
        if self.member is not None:
            data = CP_MAGIC + data
        index = target.core.propose(data)
        target.pump()
        waiter = {"member": target, "index": index, "epoch": epoch,
                  "commit_cb": commit_cb, "done": False, "ok": False,
                  "deadline": self.sim.engine.clock.elapsed()
                  + self.TIMEOUT}
        self._pending[(target.id, index)] = waiter
        self.stats["proposed"] += 1
        return waiter

    def wait_proposal(self, waiter: dict) -> None:
        eng = self.sim.engine
        while not waiter["done"]:
            m = waiter["member"]
            if not m.alive or m.stopped:
                # the proposing member is gone: its store can never run
                # the commit callback, so the proposal fails here even
                # if the entry later commits cluster-wide (a real
                # manager rebuilds its store from the WAL on restart)
                self._fail(waiter)
                break
            if waiter["epoch"] is not None \
                    and m.core.leadership_epoch != waiter["epoch"]:
                # fenced: deposed (or deposed-and-re-elected) since this
                # proposal was created — fail fast, don't wait for the
                # commit outcome
                self._fail(waiter)
                break
            if m.core.role != LEADER \
                    and m.core.commit_index < waiter["index"]:
                self._fail(waiter)   # deposed before the entry committed
                break
            if eng.clock.elapsed() >= waiter["deadline"]:
                if waiter["epoch"] is not None and m.core.role == LEADER \
                        and m.core.leadership_epoch == waiter["epoch"]:
                    # a bound proposal is never abandoned while its reign
                    # lasts (RaftNode has no proposal timeout either, by
                    # design): failing it here would orphan an entry that
                    # can still commit — and later apply BEHIND a newer
                    # proposal's store write, inverting apply order on
                    # the leader store.  Check-quorum deposes an isolated
                    # leader within ~2 election timeouts, which fences
                    # the epoch and fails this waiter properly.
                    waiter["deadline"] = eng.clock.elapsed() + self.TIMEOUT
                else:
                    self._fail(waiter)
                    break
            eng.run_until(eng.clock.elapsed() + self.PUMP)
        if not waiter["ok"]:
            self.stats["dropped"] += 1
            raise ProposalDropped("sim raft proposal dropped")
        self.stats["committed"] += 1

    def propose(self, actions, commit_cb=None, epoch=None) -> None:
        self.wait_proposal(self.propose_async(actions, commit_cb,
                                              epoch=epoch))

    # ----------------------------------------------------- read barrier

    def _skew_active(self) -> bool:
        return bool(self.sim.engine.clock_skew_members)

    def read_barrier(self, timeout: Optional[float] = None) -> dict:
        """Linearizable read barrier on THIS member (the store's
        ``read_view(linearizable=True)`` capability): resolve the
        cluster's confirmed commit index through the raft read-index
        protocol (leader-lease fast path when the core's lease is valid
        and no clock-skew fault is live), then pump virtual time until
        this member's applied state — including deferred store entries —
        covers it.  Works on leaders AND followers; raises
        ReadUnavailable when no leader confirms within ``timeout``
        (degraded, never stale).  The ReadInvariants observer judges
        every serve."""
        from ..utils.metrics import registry as _metrics
        m = self.member
        if m is None:
            return {"lease": False, "index": 0}
        eng = self.sim.engine
        obs = self.read_observer
        token = obs.begin_read(m) if obs is not None else None
        self.read_stats["reads"] += 1
        t0 = eng.clock.elapsed()
        if not self.enforce_read_barrier:
            # sensitivity seam: serve the local view unverified — the
            # follower-reads-never-uncommitted checker must fire when
            # this member trails the committed frontier
            if obs is not None:
                obs.served(m, token, lease=False,
                           skew_active=self._skew_active())
            return {"lease": False, "index": m.core.applied_index}
        deadline = t0 + (self.TIMEOUT if timeout is None else timeout)
        store0 = m.store
        core = m.core
        minted: List[int] = []
        seq: Optional[int] = None
        asked_at = t0
        barrier = lease = None
        while True:
            if not m.alive or m.stopped or m.store is not store0:
                # crashed (or crash-restarted onto a rebuilt store) mid-
                # barrier: the caller's view object is dead — fail, never
                # serve it
                self.read_stats["unavailable"] += 1
                raise ReadUnavailable(f"{m.id} went down mid-read")
            core = m.core   # a restart swaps the core object
            if seq is None:
                seq = core.request_read()
                asked_at = eng.clock.elapsed()
                if seq is not None:
                    minted.append(seq)
                    m.pump()   # flush the read_index message out
            if seq is not None:
                res = core.read_results.pop(seq, None)
                if res is not None:
                    index, ok, is_lease = res
                    if ok:
                        barrier, lease = index, is_lease
                        break
                    seq = None   # refused: retry against whoever leads
                elif eng.clock.elapsed() - asked_at >= self.READ_RETRY:
                    seq = None   # silence: the asked leader is likely gone
            if eng.clock.elapsed() >= deadline:
                self.read_stats["unavailable"] += 1
                _metrics.counter(
                    'swarm_lease_reads{result="unavailable"}')
                for s in minted:
                    core.read_results.pop(s, None)
                raise ReadUnavailable(
                    f"{m.id}: no leader confirmed a read barrier "
                    f"within {deadline - t0:.1f}s")
            eng.run_until(eng.clock.elapsed() + self.PUMP)
        for s in minted:
            core.read_results.pop(s, None)
        # local catch-up: applied index past the barrier AND the store
        # apply backlog drained (deferred entries are committed-but-
        # unapplied — serving over them would miss sealed changes)
        while True:
            if not m.alive or m.stopped or m.store is not store0:
                self.read_stats["unavailable"] += 1
                raise ReadUnavailable(f"{m.id} went down mid-read")
            m._drain_deferred()
            if core.applied_index >= barrier \
                    and not m._deferred_entries:
                break
            if eng.clock.elapsed() >= deadline:
                self.read_stats["unavailable"] += 1
                raise ReadUnavailable(
                    f"{m.id}: applied {core.applied_index} never "
                    f"reached the read barrier {barrier}")
            eng.run_until(eng.clock.elapsed() + self.PUMP)
        self.read_stats["lease" if lease else "read_index"] += 1
        _metrics.counter('swarm_lease_reads{result="lease"}' if lease
                         else 'swarm_lease_reads{result="read_index"}')
        # same meaning as RaftNode's export: last read lease-served?
        _metrics.gauge("swarm_lease_enabled", 1.0 if lease else 0.0)
        _metrics.timer("swarm_read_index_latency").observe(
            eng.clock.elapsed() - t0)
        if obs is not None:
            obs.served(m, token, lease=lease,
                       skew_active=self._skew_active())
        return {"lease": lease, "index": barrier}

    # ------------------------------------------------------------ apply tap

    def _on_apply(self, member_id: str, entry) -> bool:
        """Apply-path waiter completion; returns True when this tap
        consumed the entry (ran/settled the commit callback)."""
        waiter = self._pending.pop((member_id, entry.index), None)
        if waiter is None or waiter["done"]:
            return False
        if waiter["epoch"] is not None:
            core = waiter["member"].core
            stale = (core.role != LEADER
                     or core.leadership_epoch != waiter["epoch"])
            if stale:
                if self.enforce_fencing:
                    # commit-delivery fence: the entry committed but its
                    # reign is over — the proposer sees failure and the
                    # member store converges via the remote-apply path
                    # (we return False so _apply replays it)
                    self.stats["stale_epoch_rejects"] += 1
                    waiter["done"] = True
                    waiter["ok"] = False
                    return False
                if self.violations is not None:
                    # fencing disabled (checker-sensitivity): the stale
                    # commit callback WILL run — that is the safety
                    # violation this invariant exists to catch
                    self.violations.record(
                        "no-stale-epoch-commit",
                        f"{member_id} ran a commit callback for entry "
                        f"{entry.index} proposed under epoch "
                        f"{waiter['epoch']} (current "
                        f"{core.leadership_epoch}, role {core.role})")
        ok = True
        if waiter["commit_cb"] is not None:
            try:
                waiter["commit_cb"]()
            except Exception:
                ok = False
        waiter["ok"] = ok
        waiter["done"] = True
        return True

    def _fail(self, waiter: dict) -> None:
        self._pending.pop((waiter["member"].id, waiter["index"]), None)
        waiter["done"] = True
        waiter["ok"] = False


class SimControlPlane:
    """Standalone-mode control plane: one leader store + real Scheduler
    + real Dispatcher, driven synchronously under virtual time while the
    consensus layer churns alongside.  The raft-attached mode
    (``RaftControlPlane`` below) is what the failover scenarios run."""

    def __init__(self, engine: SimEngine, violations: Violations,
                 n_agents: int, control_interval: float = 0.5):
        self.engine = engine
        self.stopped = False
        self.busy = False   # agent-step guard (shared SimAgent surface)
        self.store = MemoryStore()
        self.invariants = TaskInvariants(violations, self.store)
        self.dispatcher = Dispatcher(
            self.store,
            DispatcherConfig(heartbeat_period=2.0, heartbeat_epsilon=0.2,
                             grace_multiplier=3.0, rate_limit_period=0.0,
                             orphan_timeout=20.0),
            rng=engine.fork_rng())
        # pipeline_depth=1: the committer thread of the pipelined tick
        # would break the sim's single-threaded determinism contract;
        # chunk-pipelined PROPOSALS (store-level, single-threaded) are
        # exercised by the pipelined-commit-churn scenario instead
        self.scheduler = Scheduler(self.store, pipeline_depth=1)
        self.scheduler.pipeline.add_filter(
            VolumesFilter(self.scheduler.volumes))
        self._task_seq = 0
        self._replaced: set = set()
        self.service = Service(
            id="svc-sim",
            spec=ServiceSpec(
                annotations=Annotations(name="sim"),
                mode=ServiceMode.REPLICATED,
                replicated=ReplicatedService(replicas=0),
                task=TaskSpec()),
            spec_version=Version(index=1))
        self.store.update(lambda tx: tx.create(self.service))

        self.agents: List[SimAgent] = []
        for i in range(n_agents):
            node = Node(
                id=f"w{i}",
                spec=NodeSpec(annotations=Annotations(name=f"w{i}")),
                status=NodeStatus(state=NodeState.UNKNOWN),
                description=NodeDescription(
                    hostname=f"w{i}",
                    resources=Resources(nano_cpus=8 * 10 ** 9,
                                        memory_bytes=32 << 30)))
            self.store.update(lambda tx, n=node: tx.create(n))
            self.agents.append(SimAgent(f"w{i}", self))

        # dispatcher up, worker thread replaced by control_step
        self.dispatcher.run(start_worker=False)
        self.store.view(self.scheduler._setup_tasks_list)
        engine.every(control_interval, "control step", self.control_step)

    # -------------------------------------------------------------- workload

    def create_tasks(self, n: int) -> None:
        def cb(tx):
            for _ in range(n):
                self._task_seq += 1
                tx.create(Task(
                    id=f"t{self._task_seq:05d}",
                    service_id=self.service.id,
                    slot=self._task_seq,
                    desired_state=TaskState.RUNNING,
                    spec=self.service.spec.task,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        self.store.update(cb)
        self.engine.log(f"workload create {n} tasks")

    # ---------------------------------------------------------- control loop

    def control_step(self) -> object:
        if self.stopped:
            return False
        self.dispatcher.process_deadlines()
        self.dispatcher._flush_updates()
        self.scheduler._resync()
        n = self.scheduler.tick()
        if n:
            self.engine.log(f"scheduler assigned {n}")
        self._restart_step()
        self.invariants.drain()
        return None

    def _restart_step(self) -> None:
        """Minimal orchestrator stand-in: replace terminal tasks whose
        desired state is still RUNNING (new task id, same slot — the
        restart supervisor's contract; the full orchestrators are
        exercised separately by the block-contract tests)."""
        tasks = self.store.view(lambda tx: tx.find(Task))
        to_replace = [
            t for t in sorted(tasks, key=lambda t: t.id)
            if TaskState(t.status.state) in TERMINAL_STATES
            and t.desired_state == TaskState.RUNNING
            and t.id not in self._replaced]
        if not to_replace:
            return

        def cb(tx):
            for t in to_replace:
                self._replaced.add(t.id)
                cur = tx.get(Task, t.id)
                if cur is not None:
                    cur = cur.copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    tx.update(cur)
                self._task_seq += 1
                tx.create(Task(
                    id=f"t{self._task_seq:05d}",
                    service_id=self.service.id,
                    slot=t.slot,
                    desired_state=TaskState.RUNNING,
                    spec=self.service.spec.task,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        self.store.update(cb)
        self.engine.log(f"restart replaced {len(to_replace)}")


class SimMemberControl:
    """The real control plane cold-started on ONE member's replicated
    store: scheduler, dispatcher, restart supervisor, and the
    replicated + global orchestrators, all writing through the member's
    epoch-fenced ``SimRaftProposer`` and all driven synchronously by
    ``step()`` under virtual time.  Built when the member becomes the
    ready leader; ``detach()``-ed (by the member's own role-transition
    handler) the instant it is deposed."""

    def __init__(self, member: SimManager, cp: "RaftControlPlane"):
        from ..orchestrator import (
            GlobalOrchestrator, JobsOrchestrator, ReplicatedOrchestrator,
            RestartSupervisor,
        )
        from ..orchestrator.update import Supervisor as UpdateSupervisor
        self.member = member
        self.cp = cp
        self.detached = False
        store = member.store
        self.store = store
        store.pipeline_depth = cp.store_pipeline_depth
        if cp.block_proposal_max_items is not None:
            store.BLOCK_PROPOSAL_MAX_ITEMS = cp.block_proposal_max_items
        self.dispatcher = Dispatcher(
            store,
            DispatcherConfig(heartbeat_period=2.0, heartbeat_epsilon=0.2,
                             grace_multiplier=3.0, rate_limit_period=0.0,
                             orphan_timeout=20.0),
            rng=cp.engine.fork_rng(),
            # follower-served mode: sessions live on the per-member read
            # planes — the leader's control dispatcher owns no shard and
            # must not grace-DOWN nodes that never register with it
            shard_filter=(lambda nid: False) if cp.follower_reads
            else None)
        cp.apply_overload_seams(self.dispatcher)
        from ..manager.allocator import Allocator
        self.allocator = Allocator(store)
        self.restarts = RestartSupervisor(store, start_worker=False)
        planner = cp.planner_factory() if cp.planner_factory else None
        # scheduler pipeline_depth=1: the tick committer THREAD would
        # break determinism; store-level chunk-pipelined proposals
        # (pipeline_depth above) are the pipelining under test here
        self.scheduler = Scheduler(store, batch_planner=planner,
                                   pipeline_depth=1,
                                   preempt_budget=cp.preempt_budget,
                                   preempt_cooldown=cp.preempt_cooldown,
                                   tick_budget_s=cp.tick_budget_s)
        # checker-sensitivity seam: preemption off means a feasible
        # higher-priority task can starve — no-priority-inversion fires
        self.scheduler.preempt_enabled = cp.preemption_enabled
        # checker-sensitivity seam: quota enforcement off means a
        # bursting tenant's committed usage runs past its quota —
        # quota-never-exceeded fires
        self.scheduler.quota_enabled = cp.quota_enabled
        self.scheduler.pipeline.add_filter(
            VolumesFilter(self.scheduler.volumes))
        # the autoscaler in threadless mode: step() pumps drive() under
        # virtual time; decisions read the scenario-driven sampler seam
        from ..orchestrator.autoscaler import (
            Supervisor as AutoscaleSupervisor,
        )
        self.autoscaler = AutoscaleSupervisor(
            store, sampler=cp.autoscale_sampler, start_worker=False)
        # pipeline DAG supervisor (ISSUE 16), threadless like the
        # autoscaler: release/halt verdicts ride consensus on Service
        # rows, so the successor leader's supervisor resumes them
        from ..orchestrator.pipeline import PipelineSupervisor
        self.pipeline = PipelineSupervisor(store, start_worker=False)
        # jobs orchestrator (run-to-completion work coexisting with
        # services): driven threadless like the other orchestrators, so
        # job iterations survive leader failover via the replicated store
        self.jobs = JobsOrchestrator(store, restarts=self.restarts)
        # REAL rolling-update supervisors in threadless mode: the
        # orchestrators' reconcile hands dirty slots to them, and
        # step() pumps their FSMs under virtual time — spec rollouts
        # (parallelism, delay, monitor window, pause/rollback) run
        # through consensus exactly like production, zero threads
        self.replicated = ReplicatedOrchestrator(
            store, restarts=self.restarts,
            updater=UpdateSupervisor(store, self.restarts,
                                     start_worker=False))
        self.global_ = GlobalOrchestrator(
            store, restarts=self.restarts,
            updater=UpdateSupervisor(store, self.restarts,
                                     start_worker=False))
        # (orchestrator, subscription, tick) driver tuples — the event
        # loops of the real orchestrators, minus their threads
        self._drivers: List[tuple] = []

    def cold_start(self) -> None:
        """Adopt the replicated store: dispatcher up, scheduler mirrors
        built, orchestrators init'd + startup task-consistency pass
        (taskinit re-arms the previous leader's delayed restarts).
        Store writes here ride consensus — the caller handles a
        mid-cold-start deposal by detaching and retrying later."""
        from ..orchestrator import taskinit
        store = self.store
        self.dispatcher.run(start_worker=False)
        store.view(self.scheduler._setup_tasks_list)
        # allocator first: it moves NEW tasks to PENDING — the state the
        # scheduler and orchestrators act on
        sub = store.queue.subscribe(accepts_blocks=True)
        self._drivers.append((self.allocator, sub, self.allocator._tick))
        self.allocator._resync()
        for orch, tick in ((self.replicated, self.replicated._tick),
                           (self.global_, self.global_._tick_tasks),
                           (self.jobs, self.jobs._tick)):
            sub = store.queue.subscribe(accepts_blocks=True)
            self._drivers.append((orch, sub, tick))
            taskinit.check_tasks(store, store.view(), orch, self.restarts)
            orch._resync()

    def step(self) -> None:
        """One synchronous control-plane step, mirroring the production
        loops' cadence: dispatcher deadlines + status flush, scheduler
        resync/preassigned/tick, orchestrator event intake + ticks,
        restart timer pump.  Aborts between phases once detached — a
        deposal can land inside any store write below."""
        from ..state.events import Event, EventSnapshotRestore
        self.dispatcher.process_deadlines()
        if self.detached:
            # a deposal landed inside process_deadlines' store write:
            # the buffered statuses die with the reign (detach chose
            # dispatcher.stop(flush=False)) — flushing them here would
            # be the deposed-loops-still-writing bug the invariant hunts
            return
        self.dispatcher._flush_updates()
        if self.detached:
            return
        self.scheduler._resync()
        if self.scheduler.pending_preassigned_tasks:
            self.scheduler._process_preassigned_tasks()
        n = self.scheduler.tick()
        if n:
            self.cp.engine.log(f"scheduler assigned {n}")
        for orch, sub, tick in self._drivers:
            if self.detached:
                return
            while True:
                ev = sub.poll()
                if ev is None:
                    break
                if isinstance(ev, EventSnapshotRestore):
                    orch._resync()
                elif isinstance(ev, Event):
                    orch._handle_event(ev)
            tick()
        # pump the rolling-update FSMs (their store writes ride
        # consensus; a deposal inside one propagates like any other
        # control write and the caller detaches)
        for orch in (self.replicated, self.global_):
            if self.detached:
                return
            orch.updater.drive()
        if self.detached:
            return
        # autoscale decisions ride consensus like every control write;
        # a deposal inside one propagates and the caller detaches
        self.autoscaler.drive()
        if self.detached:
            return
        # pipeline release/halt verdicts ride consensus the same way
        self.pipeline.drive()
        if self.detached:
            return
        self.restarts.drive()

    def detach(self) -> None:
        """Tear the loops down WITHOUT writing to the store: a deposed
        member's buffered work must die with its reign (the successor
        re-learns everything from the replicated store + agent
        re-registration), and detach can run nested inside one of this
        member's own in-flight proposals, where a store write would
        deadlock the single thread."""
        if self.detached:
            return
        self.detached = True
        for orch in (self.replicated, self.global_):
            try:
                # threadless cancel: aborts in-flight rollouts without
                # store writes; the successor's reconcile resumes them
                # from the replicated update_status
                orch.updater.cancel_all()
            except Exception:
                pass
        try:
            self.restarts.stop()     # cancels delayed starts; threadless
        except Exception:
            pass
        try:
            self.autoscaler.stop()   # never writes; threadless no-op+flag
        except Exception:
            pass
        try:
            self.pipeline.stop()     # never writes; threadless no-op+flag
        except Exception:
            pass
        for _, sub, _ in self._drivers:
            try:
                self.store.queue.unsubscribe(sub)
            except Exception:
                pass
        self._drivers.clear()
        try:
            self.dispatcher.stop(flush=False)
        except Exception:
            pass


class _LeaderWriteProxy:
    """Write-side store surface for a follower-mode dispatcher: every
    session-mutating write routes to the CURRENT leader's replicated
    store (and from there through consensus back to every member's local
    store, where the follower-served reads pick it up).  Raises
    DispatcherError during leaderless gaps — the dispatcher's flush
    paths re-queue and retry."""

    def __init__(self, cp: "RaftControlPlane"):
        self.cp = cp

    def _store(self) -> MemoryStore:
        mc = self.cp.active
        if mc is None or mc.detached or not mc.member.alive:
            raise DispatcherError("no leader to forward the write to")
        return mc.store

    def batch(self, cb):
        return self._store().batch(cb)

    def update(self, cb):
        return self._store().update(cb)


class SimWatcher:
    """A watch-stream consumer pinned to follower members: attaches to a
    member's replicated store through the REAL WatchServer surface,
    consumes Task events with resume tokens, and on member loss (crash,
    rebuild, overflow, promotion to leader) reattaches to a DIFFERENT
    member resuming from its token — the payload stream must stay
    gap-free and dup-free across every hop (WatchContinuity judges it at
    scenario end).  ``ResumeCompacted`` is handled by snapshot re-sync:
    re-list from a current view and open a fresh continuity segment."""

    def __init__(self, cp: "RaftControlPlane", name: str, request,
                 interval: float = 0.5):
        from ..manager.watchapi import compile_filter
        from .invariants import WatchContinuity
        self.cp = cp
        self.name = name
        self.engine = cp.engine
        self.request = request
        self.index = len(cp.watchers)   # spreads watchers over members
        self.continuity = WatchContinuity(
            cp.violations, compile_filter(request), cp.sim.managers,
            tag=name)
        #: checker-sensitivity seam: added to the resume token on every
        #: reattach (-1 re-delivers the last event = dup; +1 skips the
        #: next = gap); 0 in correct operation
        self.resume_skew = 0
        self.member: Optional[SimManager] = None
        self._store = None
        self.stream = None
        self.token: Optional[int] = None
        #: continuity segments: {"start": version, "events": [(v, a, id)]}
        #: — a new segment opens only on snapshot re-sync
        self.segments: List[dict] = []
        self.hops = 0
        self.resyncs = 0
        self.events_seen = 0
        self._rng = cp.engine.fork_rng()
        cp.engine.every(interval, f"watcher {name}", self.step,
                        phase=self._rng.random() * interval)

    def _pick_member(self) -> Optional[SimManager]:
        members = self.cp.sim.managers
        leader = self.cp.sim.leader()
        followers = [m for m in members
                     if m.alive and m.store is not None
                     and m is not leader]
        if followers:
            return followers[(self.index + self.hops) % len(followers)]
        return next((m for m in members
                     if m.alive and m.store is not None), None)

    def _attach(self) -> None:
        from ..manager.watchapi import ResumeCompacted, WatchServer
        m = self._pick_member()
        if m is None:
            return
        if self.stream is not None:
            try:
                self.stream.close()
            except Exception:
                pass
            self.stream = None
        if self.member is not None and m is not self.member:
            self.hops += 1
        self.member = m
        self._store = m.store
        server = WatchServer(m.store)
        if self.token is None:
            # first attach: start the stream (and its continuity
            # segment) at the member's current version
            self.token = m.store.version
            self.segments.append({"start": self.token, "events": []})
            req = self._req(self.token)
            self.stream = server.watch(req)
            self.engine.log(f"watcher {self.name} attach {m.id} "
                            f"v{self.token}")
            return
        try:
            self.stream = server.watch(
                self._req(self.token + self.resume_skew))
            self.engine.log(f"watcher {self.name} resume {m.id} "
                            f"v{self.token}")
        except ResumeCompacted:
            # snapshot re-sync: the changelog no longer reaches the
            # token — re-list from a current view and restart continuity
            self.resyncs += 1
            self.token = m.store.version
            self.segments.append({"start": self.token, "events": []})
            self.stream = server.watch(self._req(self.token))
            self.engine.log(f"watcher {self.name} resync {m.id} "
                            f"v{self.token}")

    def _req(self, resume_from: int):
        import dataclasses
        return dataclasses.replace(self.request,
                                   resume_from_version=resume_from)

    def step(self) -> object:
        if self.cp.stopped:
            return False
        if self.cp.busy:
            # a control-plane write is mid-flight on this very stack
            # (single thread): attaching now would take watch_from's
            # update lock under the held one — catch up next step
            return None
        self.drain()
        return None

    def drain(self) -> None:
        m = self.member
        stale = (m is None or not m.alive or m.store is not self._store
                 or self.stream is None or self.stream.closed)
        leader = self.cp.sim.leader()
        if not stale and m is leader:
            # drain off a freshly promoted leader: consumers belong on
            # followers (when any are available)
            if any(x for x in self.cp.sim.managers
                   if x.alive and x.store is not None and x is not m):
                stale = True
        if stale:
            self._attach()
            if self.stream is None:
                return
        while True:
            ev = self.stream.poll()
            if ev is None:
                break
            if not self.segments:
                self.segments.append({"start": 0, "events": []})
            self.segments[-1]["events"].append(
                (ev.version, ev.action, ev.obj.id))
            self.token = ev.version
            self.events_seen += 1
        self.cp.count_read(self.member)


class RaftControlPlane:
    """Raft-attached control plane (ROADMAP item 8): every member holds
    a replicated store, the full control plane runs on the current
    leader only, and leadership hand-off is driven by the members' own
    role transitions — stop the old leader's loops, fence its epoch,
    cold-start on the successor from the replicated store.

    Safety is watched continuously by two invariants on top of the
    shared checkers:

    * ``control-loops-only-on-leader`` — every control step verifies the
      attached loops belong to a live, current leader; a deposed member
      still holding loops is a violation (the transition handler must
      have detached it).
    * ``no-stale-epoch-commit`` — recorded by the member-bound proposers
      when a commit callback would run under a fenced epoch (only
      reachable with ``enforce_fencing`` disabled; the
      checker-sensitivity test proves the checker fires).
    """

    def __init__(self, engine: SimEngine, violations: Violations,
                 sim: "Sim", n_agents: int,
                 control_interval: float = 0.5):
        self.engine = engine
        self.violations = violations
        self.sim = sim
        self.n_agents = n_agents
        self.stopped = False
        self.busy = False
        self.active: Optional[SimMemberControl] = None
        # scenario knobs, applied at (re)attach time
        self.planner_factory: Optional[Callable[[], object]] = None
        self.store_pipeline_depth = 1
        self.block_proposal_max_items: Optional[int] = None
        #: checker-sensitivity seam: False breaks the detach-on-deposal
        #: handler so control-loops-only-on-leader must fire
        self.detach_on_depose = True
        self.desired_replicas = 0
        self._bootstrapped = False
        self.attaches = 0
        # ---- rolling-update workload surface
        #: spec versions whose tasks die on startup (rollout-poison
        #: fault, consumed by SimAgent); healed by Sim.finish
        self.poison_versions: set = set()
        #: service ids whose tasks die on startup (stage-poison fault,
        #: pipeline-chaos); healed by Sim.finish like poison_versions
        self.poison_services: set = set()
        #: monotone spec-version mint for rollout(); the bootstrap
        #: service is version 1
        self._next_version = 1
        #: FIFO of not-yet-applied rollouts — a queue, not a slot: a
        #: rollout minted while an earlier one is still retrying across
        #: a failover gap must not drop it (its registered expectation
        #: would turn into a false convergence violation)
        self._pending_rollouts: List[tuple] = []
        self.rollouts = 0
        #: scenario-registered convergence expectations, judged at
        #: finish against the merged update-state history:
        #: (version, frozenset of UpdateState ints, by_virtual_ts, label)
        self.update_expectations: List[tuple] = []
        #: opt-in post-convergence placement-quality bound (see
        #: invariants.check_placement_quality); None disables
        self.placement_quality_bound: Optional[float] = None
        # ---- autoscaler + tenant QoS scenario surface (ISSUE 12)
        #: checker-sensitivity seam: False disables the scheduler's
        #: quota plane so quota-never-exceeded must fire
        self.quota_enabled = True
        #: scenario-driven per-service load (demand units) feeding the
        #: autoscalers' sampler seam deterministically
        self.service_load: Dict[str, float] = {}
        #: (kind, sid, replicas, by, label) autoscale expectations:
        #: kind "reach" = some committed change >= replicas by ``by``;
        #: kind "converge" = back at exactly ``replicas`` by ``by`` AND
        #: at scenario end
        self.autoscale_expectations: List[tuple] = []
        #: (min_priority, t0, t1) burst windows for the cross-band p99
        #: invariant
        self.band_p99_expectations: List[tuple] = []
        #: archived QoS material from crash-replaced checkers
        self._qos_replicas_archive: List[tuple] = []
        self._qos_samples_archive: List[tuple] = []
        #: cumulative quota clamps across leader attach epochs (+ the
        #: one-shot "fault quota-clamp scheduler" coverage line)
        self.quota_clamp_total = 0
        self._quota_clamps_prev = 0
        # ---- priority & preemption scenario surface
        #: checker-sensitivity seam: False disables the scheduler's
        #: preemption pass so no-priority-inversion must fire
        self.preemption_enabled = True
        #: scheduler knobs, applied at (re)attach (None = defaults)
        self.preempt_budget: Optional[int] = None
        self.preempt_cooldown: Optional[float] = None
        #: PreemptionInvariants knobs (per-member checkers)
        self.preempt_inversion_bound = 25.0
        self.preempt_thrash_bound = 3
        #: end-state expectation: the scenario requires >= 1 preemption
        #: to have been observed (coverage, not safety)
        self.expect_preemptions = False
        #: (service_id, total_completions) end-state job expectations
        self.job_expectations: List[tuple] = []
        # ---- gang & pipeline scenario surface (ISSUE 16)
        #: (service_id, want_running, label) end-state expectations: the
        #: service must show >= want_running RUNNING tasks at finish
        self.service_expectations: List[tuple] = []
        #: (service_id, pipeline state, label) end-state expectations on
        #: the replicated PipelineStatus verdict
        self.pipeline_expectations: List[tuple] = []
        #: preemption records archived from crash-replaced checkers
        self._preempt_archive: List[tuple] = []
        self._dispatcher_totals = {"heartbeats": 0, "expirations": 0,
                                   "sheds": 0, "hb_stretches": 0,
                                   "premature_expirations": 0}
        # ---- overload-protection plane (ISSUE 20)
        #: DispatcherConfig field overrides (max_sessions,
        #: hb_stretch_start, max_pending_updates, max_terminal_tasks,
        #: ...) applied to EVERY dispatcher the plane builds — the
        #: leader's control dispatcher and the follower read planes
        self.dispatcher_overrides: Dict[str, object] = {}
        #: scheduler tick deadline budget (virtual seconds; None = off),
        #: applied at (re)attach
        self.tick_budget_s: Optional[float] = None
        #: checker-sensitivity seam: False makes heartbeat-period
        #: stretching promise a long window but enforce the UNstretched
        #: deadline — heartbeat-liveness-under-stretch must fire
        self.stretch_extends_deadline = True
        #: checker-sensitivity seam: False sheds WITHOUT counting —
        #: overload-sheds-are-counted-and-recovered must fire
        self.count_sheds = True
        self.overload_inv = OverloadInvariants(violations, self)
        self._sheds_prev = 0
        self._hb_stretches_prev = 0
        # ---- follower-served read plane (ISSUE 11)
        #: scenario knob: serve agent sessions + watch streams from the
        #: members' replicated stores (sharded by node-id hash), writes
        #: forwarded to the leader
        self.follower_reads = False
        #: node id -> member id currently owning its session (shared so
        #: a sharded dispatcher never DOWNs a node registered elsewhere)
        self.session_owner: Dict[str, str] = {}
        self._planes: Dict[str, tuple] = {}   # member id -> (store, disp)
        self._member_was_alive: Dict[str, bool] = {}
        self.read_inv = ReadInvariants(violations, sim.managers)
        self.watchers: List[SimWatcher] = []
        self.read_stats = {"reads_leader": 0, "reads_follower": 0,
                           "probe_ok": 0, "probe_unavailable": 0,
                           "agent_reconnects": 0, "stale_probe_refused": 0}
        #: end-state expectation (read-storm scenarios): probes must
        #: degrade to read-index latency, never fail outright
        self.expect_reads_never_fail = False
        self.proposers: Dict[str, SimRaftProposer] = {}
        for m in sim.managers:
            p = SimRaftProposer(sim, member=m, violations=violations)
            p.read_observer = self.read_inv
            m.store._proposer = p
            m.store_proposer = p     # survives store rebuilds (restart)
            self.proposers[m.id] = p
            m.transition_hooks.append(self._member_transition)
        # per-member-store task invariants (rebuilt when a restart
        # replaces the store object)
        self._inv: Dict[str, tuple] = {}
        # update-state history outlives checker replacement: a member
        # whose store was crash-rebuilt gets a fresh checker, but the
        # states its old checker observed still count toward the
        # convergence expectations
        self._update_history: List[tuple] = []
        # the p99 bound's cadence: one control step + the scheduler's
        # commit-debounce ceiling — the scheduler's own latency model,
        # not a per-scenario constant (QosInvariants.band_p99_bound)
        from ..scheduler.scheduler import MAX_LATENCY
        self._qos_cadence = control_interval + MAX_LATENCY
        self.agents: List[SimAgent] = [
            SimAgent(f"w{i}", self) for i in range(n_agents)]
        engine.every(control_interval, "control step", self.control_step)

    # ------------------------------------------------------- shared surface

    @property
    def store(self) -> Optional[MemoryStore]:
        """The authoritative store view: the active leader's, else the
        most-caught-up member's (stats/agents after a failover gap)."""
        if self.active is not None and not self.active.detached:
            return self.active.store
        best = None
        for m in self.sim.managers:
            if m.store is not None and (
                    best is None or m.store.version > best.version):
                best = m.store
        return best

    @property
    def dispatcher(self) -> Optional[Dispatcher]:
        mc = self.active
        if mc is None or mc.detached or not mc.member.alive:
            return None
        return mc.dispatcher

    @property
    def dispatcher_stats(self) -> Dict[str, int]:
        """Accumulated across every leader's dispatcher (attach epochs)
        and, in follower-served mode, every member's read plane."""
        totals = dict(self._dispatcher_totals)
        mc = self.active
        if mc is not None:
            for k in totals:
                totals[k] += mc.dispatcher.stats.get(k, 0)
        for _store, d in self._planes.values():
            for k in totals:
                totals[k] += d.stats.get(k, 0)
        return totals

    def apply_overload_seams(self, d: Dispatcher) -> None:
        """Overload-plane knobs + checker-sensitivity seams, applied to
        every dispatcher this plane builds (leader control plane and
        follower read planes alike — bounds are plane-wide policy)."""
        for k, v in self.dispatcher_overrides.items():
            setattr(d.config, k, v)
        d.stretch_extends_deadline = self.stretch_extends_deadline
        d.count_sheds = self.count_sheds

    # ------------------------------------------- follower-served reads

    def enable_follower_reads(self) -> None:
        """Switch the consumer plane to follower-served mode: agents
        shard their sessions across members by node-id hash (preferring
        non-leaders), served from each member's local replicated store;
        only session-mutating writes forward to the leader."""
        self.follower_reads = True

    def _shard_member_id(self, node_id: str) -> str:
        import zlib
        members = self.sim.managers
        return members[zlib.crc32(node_id.encode()) % len(members)].id

    def plane_for_id(self, member_id: str) -> Optional[Dispatcher]:
        entry = self._planes.get(member_id)
        return entry[1] if entry is not None else None

    def plane_for(self, m: SimManager) -> Optional[Dispatcher]:
        """This member's follower-mode dispatcher over its replicated
        store, rebuilt whenever a crash-restart replaced the store."""
        if not self.follower_reads or m.store is None or not m.alive:
            return None
        entry = self._planes.get(m.id)
        if entry is not None and entry[0] is m.store:
            return entry[1]
        if entry is not None:
            for k in self._dispatcher_totals:
                self._dispatcher_totals[k] += entry[1].stats.get(k, 0)
            try:
                entry[1].stop(flush=False)
            except Exception:
                pass
        d = Dispatcher(
            m.store,
            DispatcherConfig(heartbeat_period=2.0, heartbeat_epsilon=0.2,
                             grace_multiplier=3.0, rate_limit_period=0.0,
                             orphan_timeout=20.0),
            rng=self.engine.fork_rng(),
            write_store=_LeaderWriteProxy(self),
            shard_filter=lambda nid, mid=m.id:
                self.session_owner.get(nid, self._shard_member_id(nid))
                == mid)
        # a reg-grace deadline only DOWNs a node with no live session on
        # ANY member (ownership is control-plane-wide state)
        d.reg_grace_check = \
            lambda nid: self.session_owner.get(nid) is None
        self.apply_overload_seams(d)
        d.run(start_worker=False)
        if os.environ.get("SWARM_BATCH_FANOUT", "1") != "0":
            # batched assignment fan-out is the DEFAULT consumer plane
            # (ISSUE 13 satellite; opt-out escape hatch only): one store
            # subscription per plane, per-node batched flushes driven
            # from process_deadlines — the ≤⌈N/batch⌉-sends contract
            d.enable_batched_fanout()
        self._planes[m.id] = (m.store, d)
        return d

    def _reap_dead_member_sessions(self, member_id: str) -> None:
        """A member died: its sessions are orphaned.  Clear ownership and
        hand the nodes a registration-grace window on a surviving plane —
        live agents re-register elsewhere well inside it; truly dead ones
        get marked DOWN so their tasks heal."""
        orphans = [nid for nid, mid in self.session_owner.items()
                   if mid == member_id]
        if not orphans:
            return
        for nid in orphans:
            self.session_owner.pop(nid, None)
        for m in self.sim.managers:
            d = self.plane_for(m)
            if d is not None:
                d.adopt_registration_grace(orphans)
                break

    def count_read(self, member: Optional[SimManager]) -> None:
        """Attribute one consumer read to the serving member's role and
        refresh the leader-share gauge (the 'consumers off the
        coordinator' headline number)."""
        if member is None:
            return
        from ..utils.metrics import registry as _metrics
        leader = self.sim.leader()
        key = "reads_leader" if member is leader else "reads_follower"
        self.read_stats[key] += 1
        total = (self.read_stats["reads_leader"]
                 + self.read_stats["reads_follower"])
        _metrics.gauge("swarm_leader_read_share",
                       self.read_stats["reads_leader"] / total)

    def leader_read_share(self) -> float:
        total = (self.read_stats["reads_leader"]
                 + self.read_stats["reads_follower"])
        return self.read_stats["reads_leader"] / total if total else 0.0

    def linearizable_read(self, member: SimManager, cb,
                          timeout: Optional[float] = None):
        """One linearizable read served by ``member`` (leader or
        follower): runs the read barrier, serves the local view, and
        counts the read toward the leader-share gauge."""
        self.count_read(member)
        return member.store.read_view(cb, linearizable=True,
                                      timeout=timeout)

    def add_watchers(self, n: int, request=None,
                     interval: float = 0.5) -> None:
        """Attach ``n`` follower-pinned watch consumers (resume-token
        continuity judged at scenario end)."""
        from ..manager.watchapi import WatchRequest
        for _ in range(n):
            req = request if request is not None \
                else WatchRequest(kinds=[Task])
            self.watchers.append(SimWatcher(
                self, f"watch{len(self.watchers)}", req,
                interval=interval))

    def start_read_probes(self, interval: float = 1.0,
                          timeout: float = 20.0) -> None:
        """Periodic linearizable read probes round-robining the follower
        members (the read-storm workload): under churn they must degrade
        to read-index latency — outright failures are counted and, with
        ``expect_reads_never_fail``, judged at scenario end."""
        state = {"i": 0}

        def probe():
            if self.stopped or self.sim.finishing:
                return False
            if self.busy:
                return None   # a control write is mid-flight on this stack
            members = [m for m in self.sim.managers
                       if m.alive and m.store is not None]
            leader = self.sim.leader()
            cands = [m for m in members if m is not leader] or members
            if not cands:
                return None
            m = cands[state["i"] % len(cands)]
            state["i"] += 1
            try:
                self.linearizable_read(
                    m, lambda tx: len(tx.find(Task)), timeout=timeout)
                self.read_stats["probe_ok"] += 1
            except ReadUnavailable:
                self.read_stats["probe_unavailable"] += 1
                self.engine.log(f"read probe unavailable on {m.id}")
            return None

        self.engine.every(interval, "read probe", probe, phase=0.3)

    # ---------------------------------------------------------- transitions

    def _member_transition(self, member: SimManager, role: str,
                           term: int) -> None:
        mc = self.active
        if mc is not None and mc.member is member and role != LEADER:
            if self.detach_on_depose:
                # fence FIRST: even proposals already past their role
                # checks can no longer commit under the old reign
                member.core.fence_epoch()
                self._detach(f"{member.id} deposed (term {term})")

    def _detach(self, reason: str) -> None:
        mc, self.active = self.active, None
        if mc is None:
            return
        self.engine.log(f"control detach {mc.member.id}: {reason}")
        for k in self._dispatcher_totals:
            self._dispatcher_totals[k] += mc.dispatcher.stats.get(k, 0)
        self.quota_clamp_total += \
            mc.scheduler.stats.get("quota_clamps", 0)
        mc.detach()

    def quota_clamps(self) -> int:
        """Quota clamps across every leader's scheduler (attach epochs)."""
        total = self.quota_clamp_total
        mc = self.active
        if mc is not None:
            total += mc.scheduler.stats.get("quota_clamps", 0)
        return total

    def _attach(self, member: SimManager) -> None:
        # the deposal window may have left committed entries deferred
        # (the old reign's failing proposal held the store lock while
        # they applied): they MUST land before the new reign reads or
        # writes the store, or apply order inverts against the cluster
        member._drain_deferred()
        self.attaches += 1
        mc = SimMemberControl(member, self)
        self.active = mc
        self.engine.log(
            f"control attach {member.id} term={member.core.term} "
            f"epoch={member.core.leadership_epoch}")
        self.busy = True
        try:
            mc.cold_start()
        except AGENT_RPC_ERRORS as e:
            # leadership lost mid-cold-start: tear down, retry on the
            # next leader.  Anything else propagates — a broken control
            # plane must fail the scenario, not log-and-limp.
            self.engine.log(f"control cold-start aborted on {member.id}: "
                            f"{type(e).__name__}")
            self._detach("cold start failed")
        finally:
            self.busy = False

    # --------------------------------------------------------- control step

    def _checker_for(self, m: SimManager) -> Optional[tuple]:
        """(TaskInvariants, UpdateInvariants, PreemptionInvariants,
        QosInvariants, GangInvariants, PipelineInvariants) for a
        member's replicated store, rebuilt when a restart replaces the
        store object."""
        if m.store is None:
            return None
        entry = self._inv.get(m.id)
        if entry is None or entry[0] is not m.store:
            if entry is not None:
                self._update_history.extend(entry[2].history)
                self._preempt_archive.extend(entry[3].preempted)
                self._qos_replicas_archive.extend(
                    entry[4].replica_history)
                self._qos_samples_archive.extend(entry[4].band_samples)
            entry = (m.store,
                     TaskInvariants(self.violations, m.store),
                     UpdateInvariants(self.violations, m.store, tag=m.id),
                     PreemptionInvariants(
                         self.violations, m.store, tag=m.id,
                         inversion_bound=self.preempt_inversion_bound,
                         thrash_bound=self.preempt_thrash_bound),
                     QosInvariants(self.violations, m.store, tag=m.id,
                                   cadence=self._qos_cadence),
                     GangInvariants(self.violations, m.store, tag=m.id),
                     PipelineInvariants(self.violations, m.store,
                                        tag=m.id))
            self._inv[m.id] = entry
        return entry[1:]

    def drain_deferred(self) -> None:
        """Apply any backlog of committed-but-deferred entries on the
        active member's store before a control-plane write stages
        against it (see SimManager._deferred_entries)."""
        mc = self.active
        if mc is not None and mc.member.alive:
            mc.member._drain_deferred()

    def control_step(self) -> object:
        if self.stopped:
            return False
        sim = self.sim
        # deferred backlogs drain BEFORE any member's store is read or
        # written this step — a write staged over an un-drained backlog
        # would commit ahead of older log entries (order inversion)
        for m in sim.managers:
            if m.alive and m.store is not None:
                m._drain_deferred()
        mc = self.active
        if mc is not None:
            m = mc.member
            if not m.alive or m.stopped:
                self._detach(f"{m.id} crashed")
            elif m.core.role != LEADER:
                # the transition handler must have detached already; a
                # deposed member still holding live control loops is the
                # split-brain this invariant exists to catch
                self.violations.record(
                    "control-loops-only-on-leader",
                    f"{m.id} still runs control loops as {m.core.role} "
                    f"(term {m.core.term})")
                self._detach(f"{m.id} deposed (checker)")
        if self.active is None and not self.busy:
            lead = sim.leader()
            if lead is not None and lead.store is not None:
                self._attach(lead)
        mc = self.active
        if mc is not None and not self.busy:
            self.busy = True
            try:
                if not self._bootstrapped:
                    self._bootstrap(mc.store)
                mc.step()
            except AGENT_RPC_ERRORS as e:
                # leadership lost inside a store write: the loops'
                # internal rollback paths have run; the successor takes
                # over from the replicated store.  Any OTHER exception
                # propagates and fails the scenario — masking a genuine
                # control-plane crash would defeat the simulator.
                self.engine.log(
                    f"control step aborted: {type(e).__name__}")
            finally:
                self.busy = False
        # drain the per-store task + update invariants (single-threaded:
        # nothing is in flight between control steps)
        for m in sim.managers:
            checkers = self._checker_for(m)
            if checkers is not None:
                for inv in checkers:
                    inv.drain()
        if self.follower_reads:
            # member deaths orphan their session shard; survivors adopt
            # a registration-grace window for the affected nodes
            for m in sim.managers:
                was = self._member_was_alive.get(m.id, True)
                if was and not m.alive:
                    self._reap_dead_member_sessions(m.id)
                self._member_was_alive[m.id] = m.alive
            if not self.busy:
                # drive every member's follower dispatcher threadless:
                # TTL/grace deadlines + forwarded status flushes
                self.busy = True
                try:
                    for m in sim.managers:
                        d = self.plane_for(m)
                        if d is None:
                            continue
                        d.process_deadlines()
                        d._flush_updates()
                finally:
                    self.busy = False
        for w in self.watchers:
            w.continuity.ensure()
            w.continuity.drain()
        # coverage line: the first ACTUAL quota clamp marks the cell —
        # honest coverage, not a scripted log (chaos_sweep REQUIRED_CELLS)
        qc = self.quota_clamps()
        if qc and not self._quota_clamps_prev:
            self.engine.log("fault quota-clamp scheduler")
        self._quota_clamps_prev = qc
        # same honest-coverage pattern for the overload plane: the first
        # ACTUAL admission shed / heartbeat stretch marks its cell
        ds = self.dispatcher_stats
        sheds = ds.get("sheds", 0)
        if sheds and not self._sheds_prev:
            self.engine.log("fault overload-shed dispatcher")
        self._sheds_prev = sheds
        stretches = ds.get("hb_stretches", 0)
        if stretches and not self._hb_stretches_prev:
            self.engine.log("fault heartbeat-stretch agent")
        self._hb_stretches_prev = stretches
        return None

    # ----------------------------------------------- autoscaler + QoS

    def autoscale_sampler(self, service_id: str) -> Optional[dict]:
        """The supervisors' sampler seam, driven by the scenario's
        ``service_load`` — deterministic by construction (virtual time,
        no registry reads)."""
        load = self.service_load.get(service_id)
        if load is None:
            return None
        return {"load": load}

    def set_load(self, service_id: str, load: float) -> None:
        """Set the observed demand for one service (the autoscaler's
        input signal)."""
        self.service_load[service_id] = load
        self.engine.log(f"workload load {service_id}={load:g}")

    def configure_tenants(self, tenants: Dict[str, object]) -> None:
        """Create/replace the default Cluster's per-tenant quotas
        (ClusterSpec.tenants); retried across failover gaps."""
        from ..models.objects import Cluster
        from ..models.specs import ClusterSpec

        def cb(tx):
            cur = tx.get(Cluster, "cluster-default")
            if cur is None:
                tx.create(Cluster(
                    id="cluster-default",
                    spec=ClusterSpec(
                        annotations=Annotations(name="default"),
                        tenants=dict(tenants))))
            else:
                cur = cur.copy()
                cur.spec.tenants = dict(tenants)
                tx.update(cur)
        self._apply_workload(f"tenants {sorted(tenants)}", cb)

    def expect_autoscale(self, sid: str, at_least: int,
                         by: float) -> None:
        """The scale-up must commit >= ``at_least`` replicas by ``by``
        virtual seconds — across whatever failovers happen meanwhile."""
        self.autoscale_expectations.append(
            ("reach", sid, at_least, by, "autoscale-scale-up"))

    def expect_autoscale_converge(self, sid: str, to: int,
                                  by: float) -> None:
        """Load removed => replicas must return to ``to`` by ``by`` AND
        still be there at scenario end (autoscale-converges)."""
        self.autoscale_expectations.append(
            ("converge", sid, to, by, "autoscale-converges"))

    def expect_band_p99(self, min_priority: int, t0: float,
                        t1: float) -> None:
        """Register a burst window for no-cross-band-p99-violation."""
        self.band_p99_expectations.append((min_priority, t0, t1))

    def _qos_checkers(self) -> List[QosInvariants]:
        return [entry[4] for entry in self._inv.values()]

    def merged_replica_history(self) -> List[tuple]:
        """Committed replica changes, deduped across member checkers:
        every member observes the same committed change SEQUENCE per
        service (laggards see a prefix), so the merged history is the
        longest observed sequence, stamped at the earliest observation
        of each position."""
        per_source: Dict[str, List[List[tuple]]] = {}
        sources = [self._qos_replicas_archive] + [
            c.replica_history for c in self._qos_checkers()]
        for src in sources:
            by_sid: Dict[str, List[tuple]] = {}
            for t, sid, replicas in src:
                by_sid.setdefault(sid, []).append((t, replicas))
            for sid, seq in by_sid.items():
                per_source.setdefault(sid, []).append(seq)
        out: List[tuple] = []
        for sid, seqs in per_source.items():
            # one authoritative sequence per service: the longest (a
            # crash-rebuilt checker's fresh tail is shorter and its
            # changes were also observed by the surviving members);
            # ties resolve to the earliest-stamped observer
            best = min(seqs, key=lambda s: (-len(s), s[0][0] if s
                                            else 0.0))
            out.extend((t, sid, replicas) for t, replicas in best)
        out.sort()
        return out

    def _merged_band_data(self):
        """(samples, open_pending) deduped across member checkers +
        archives: every member observes the same committed stream, so
        first-writer-wins by task id."""
        samples: Dict[str, tuple] = {}
        for s in self._qos_samples_archive:
            samples.setdefault(s[0], s)
        for c in self._qos_checkers():
            for s in c.band_samples:
                samples.setdefault(s[0], s)
        open_pending: Dict[str, tuple] = {}
        for c in self._qos_checkers():
            for tid, entry in c.pending_open.items():
                if tid not in samples:
                    open_pending.setdefault(tid, entry)
        return list(samples.values()), list(open_pending.values())

    # -------------------------------------------------------------- workload

    def _bootstrap(self, store: MemoryStore) -> None:
        """First-leader bootstrap: worker Node records + the replicated
        service, replicated to every member.  Idempotent — a retry after
        a dropped-but-committed proposal skips existing objects."""
        def cb(tx):
            # every agent the scenario attached BEFORE first leadership
            # — including a MuxAgentFleet's multiplexed sessions — gets
            # its worker Node record here
            for nid in [a.node_id for a in self.agents]:
                if tx.get(Node, nid) is None:
                    tx.create(Node(
                        id=nid,
                        spec=NodeSpec(annotations=Annotations(name=nid)),
                        status=NodeStatus(state=NodeState.UNKNOWN),
                        description=NodeDescription(
                            hostname=nid,
                            resources=Resources(nano_cpus=8 * 10 ** 9,
                                                memory_bytes=32 << 30))))
            if tx.get(Service, "svc-sim") is None:
                from ..models.types import (
                    UpdateConfig, UpdateFailureAction,
                )
                # virtual-time-sized update/rollback knobs: a ROLLBACK
                # runs under the RESTORED spec's rollback config
                # (reference behavior), so the base spec must carry one
                # or rollbacks crawl at the 30s-monitor defaults.  The
                # rollback cadence pushes through churn (CONTINUE):
                # chaos-injected task failures during a rollback would
                # otherwise trip the threshold and PAUSE it (a rollback
                # never rolls back), turning unlucky seeds into
                # convergence-bound "violations" that are really
                # correct FSM behavior
                cadence = dict(parallelism=3, delay=0.2, monitor=1.5,
                               max_failure_ratio=0.2)
                tx.create(Service(
                    id="svc-sim",
                    spec=ServiceSpec(
                        annotations=Annotations(name="sim"),
                        mode=ServiceMode.REPLICATED,
                        replicated=ReplicatedService(
                            replicas=self.desired_replicas),
                        task=TaskSpec(),
                        update=UpdateConfig(**cadence),
                        rollback=UpdateConfig(
                            failure_action=UpdateFailureAction.CONTINUE,
                            **cadence)),
                    spec_version=Version(index=1)))
        store.update(cb)
        self._bootstrapped = True
        self.engine.log("workload bootstrap replicated")

    def scale(self, replicas: int) -> None:
        """Set the replicated service's replica count through the
        leader store; the replicated orchestrator materializes/removes
        tasks on its next tick.  Retries while no leader control plane
        is up (failover gaps) — deterministic, event-driven."""
        self.desired_replicas = replicas
        mc = self.active
        if (self.stopped or mc is None or mc.detached or self.busy
                or not self._bootstrapped):
            self.engine.after(0.5, "scale retry",
                              lambda: self._scale_if_current(replicas))
            return
        self.busy = True
        try:
            def cb(tx):
                svc = tx.get(Service, "svc-sim")
                if svc is None:
                    return
                svc = svc.copy()
                svc.spec.replicated.replicas = replicas
                tx.update(svc)
            mc.store.update(cb)
            self.engine.log(f"workload scale {replicas}")
        except AGENT_RPC_ERRORS as e:
            self.engine.log(f"workload scale failed: {type(e).__name__}")
            self.engine.after(0.5, "scale retry",
                              lambda: self._scale_if_current(replicas))
        finally:
            self.busy = False

    def _scale_if_current(self, replicas: int) -> None:
        # a newer scale() call supersedes the retry chain
        if replicas == self.desired_replicas:
            self.scale(replicas)

    def create_tasks(self, n: int) -> None:
        """Shared scenario surface: grow the workload by ``n`` replicas
        (the orchestrator creates the tasks — ids are deterministic via
        the sim's id source)."""
        self.scale(self.desired_replicas + n)

    # ------------------------------------------- priority / jobs workloads

    def _apply_workload(self, label: str, cb) -> None:
        """Write a workload mutation through the leader store, retrying
        across failover gaps (the scale()/rollout() discipline); ``cb``
        must be idempotent — a dropped-but-committed proposal retries."""
        mc = self.active
        if (self.stopped or mc is None or mc.detached or self.busy
                or not self._bootstrapped):
            self.engine.after(0.5, f"{label} retry",
                              lambda: self._apply_workload(label, cb))
            return
        self.busy = True
        try:
            mc.store.update(cb)
            self.engine.log(f"workload {label}")
        except AGENT_RPC_ERRORS as e:
            self.engine.log(
                f"workload {label} failed: {type(e).__name__}")
            self.engine.after(0.5, f"{label} retry",
                              lambda: self._apply_workload(label, cb))
        finally:
            self.busy = False

    def add_service(self, sid: str, replicas: int, priority: int = 0,
                    nano_cpus: int = 0, memory_bytes: int = 0,
                    tenant: str = "", autoscale=None,
                    gang_min: int = 0, gang_id: str = "",
                    depends_on=None,
                    on_upstream_failure: str = "halt") -> None:
        """Create a replicated service in a priority band, optionally
        with per-task reservations (the preemption scenarios' workload:
        bands contending for finite node capacity), a tenant label
        (quota enforcement — the ``swarm.tenant`` annotation the
        orchestrator propagates onto every task), an autoscaling
        policy, gang placement (``gang_min`` > 0 opts every task into
        an all-or-nothing unit keyed by ``gang_id`` or the service),
        and pipeline dependencies (``depends_on`` upstream service
        names gate the stage behind the PipelineSupervisor).  The
        SERVICE-level priority is used deliberately — it exercises the
        ServiceSpec.priority -> task spec propagation path."""
        from ..models.types import GangConfig, Placement, \
            ResourceRequirements
        from ..scheduler.quota import TENANT_LABEL

        def cb(tx):
            if tx.get(Service, sid) is not None:
                return
            res = ResourceRequirements(reservations=Resources(
                nano_cpus=nano_cpus, memory_bytes=memory_bytes))
            labels = {TENANT_LABEL: tenant} if tenant else {}
            placement = Placement(gang=GangConfig(min_size=gang_min)) \
                if gang_min > 0 else Placement()
            tx.create(Service(
                id=sid,
                spec=ServiceSpec(
                    annotations=Annotations(name=sid, labels=labels),
                    mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=replicas),
                    task=TaskSpec(resources=res, placement=placement,
                                  gang_id=gang_id),
                    priority=priority,
                    autoscale=autoscale,
                    depends_on=list(depends_on or ()),
                    on_upstream_failure=on_upstream_failure),
                spec_version=Version(index=1)))
        self._apply_workload(
            f"service {sid} x{replicas} prio={priority}"
            + (f" gang>={gang_min}" if gang_min > 0 else "")
            + (f" after={','.join(depends_on)}" if depends_on else ""),
            cb)

    def run_job(self, sid: str, total: int, max_concurrent: int = 0,
                priority: int = 0) -> None:
        """Create a replicated run-to-completion job (jobs orchestrator:
        ``total`` unique slots, at most ``max_concurrent`` in flight)."""
        from ..models.specs import ReplicatedJob

        def cb(tx):
            if tx.get(Service, sid) is not None:
                return
            tx.create(Service(
                id=sid,
                spec=ServiceSpec(
                    annotations=Annotations(name=sid),
                    mode=ServiceMode.REPLICATED_JOB,
                    replicated_job=ReplicatedJob(
                        total_completions=total,
                        max_concurrent=max_concurrent),
                    task=TaskSpec(),
                    priority=priority),
                spec_version=Version(index=1)))
        self._apply_workload(f"job {sid} x{total}", cb)

    def expect_job_complete(self, sid: str, total: int) -> None:
        """End-state bound: the job must show ``total`` completions."""
        self.job_expectations.append((sid, total))

    def expect_service_running(self, sid: str, running: int,
                               label: str = "gang-converges") -> None:
        """End-state bound: >= ``running`` tasks of ``sid`` RUNNING at
        finish (the gang scenarios' convergence claim: every deferred
        gang eventually placed in full)."""
        self.service_expectations.append((sid, running, label))

    def expect_pipeline_state(self, sid: str, state: str,
                              label: str = "pipeline-converges") -> None:
        """End-state bound on the replicated pipeline verdict of
        ``sid`` ("released" / "halted" / "waiting")."""
        self.pipeline_expectations.append((sid, state, label))

    # --------------------------------------------------------- spec rollouts

    def rollout(self, image: str, update=None, rollback=None,
                poison: bool = False) -> int:
        """Spec-bump the sim service to ``image`` through the leader
        store (controlapi.update_service shape: previous spec saved,
        spec version minted, update_status cleared) — the replicated
        orchestrator's UpdateSupervisor then rolls the slots over.
        ``poison=True`` marks the minted version so agents fail its
        tasks on startup (exercising pause/rollback).  Retries across
        failover gaps; returns the minted spec version index."""
        self._next_version += 1
        version = self._next_version
        if poison:
            self.poison_versions.add(version)
        self._pending_rollouts.append((image, version, update, rollback))
        self.rollouts += 1
        self.engine.log(f"workload rollout {image} v{version}"
                        + (" poisoned" if poison else ""))
        self._rollout_step()
        return version

    def _rollout_step(self) -> None:
        if not self._pending_rollouts or self.stopped:
            return
        pending = self._pending_rollouts[0]
        image, version, update, rollback = pending
        mc = self.active
        if (mc is None or mc.detached or self.busy
                or not self._bootstrapped):
            self.engine.after(0.5, "rollout retry", self._rollout_step)
            return
        self.busy = True
        try:
            def cb(tx):
                svc = tx.get(Service, "svc-sim")
                if svc is None:
                    return
                if svc.spec_version and svc.spec_version.index >= version:
                    return   # already applied (idempotent retry)
                svc = svc.copy()
                old_spec = svc.spec
                spec = old_spec.copy()
                spec.task = spec.task.copy()
                from ..models.specs import ContainerSpec
                spec.task.container = ContainerSpec(image=image)
                if update is not None:
                    spec.update = update
                if rollback is not None:
                    spec.rollback = rollback
                svc.previous_spec = old_spec
                svc.previous_spec_version = svc.spec_version
                svc.spec = spec
                svc.spec_version = Version(index=version)
                svc.update_status = None
                tx.update(svc)
            mc.store.update(cb)
            if self._pending_rollouts \
                    and self._pending_rollouts[0] is pending:
                self._pending_rollouts.pop(0)
            self.engine.log(f"workload rollout applied v{version}")
            if self._pending_rollouts:
                # a queued successor (minted during a failover gap)
                # applies on its own step, not inside this one's
                # busy window
                self.engine.after(0.0, "rollout next", self._rollout_step)
        except AGENT_RPC_ERRORS as e:
            self.engine.log(f"workload rollout failed: {type(e).__name__}")
            self.engine.after(0.5, "rollout retry", self._rollout_step)
        finally:
            self.busy = False

    def expect_update(self, version: int, states, by: float,
                      label: str = "update-convergence-within-bound"
                      ) -> None:
        """Register a convergence bound: version must be observed in one
        of ``states`` (UpdateState values) by virtual time ``by``."""
        self.update_expectations.append(
            (version, frozenset(int(s) for s in states), by, label))

    # ----------------------------------------------------- end-state checks

    def _update_checkers(self) -> List[UpdateInvariants]:
        return [entry[2] for entry in self._inv.values()]

    def merged_update_history(self) -> List[tuple]:
        """Archived history (from crash-replaced checkers) + every live
        checker's — the single source both finish-time judging and the
        stats report read."""
        history = list(self._update_history)
        history.extend(h for c in self._update_checkers()
                       for h in c.history)
        return history

    def check_end_state(self, violations: Violations) -> None:
        """Finish-time checks: flush deferred completion checks, judge
        the registered convergence expectations against the merged
        per-member histories (any member observing a state counts —
        a crash-rebuilt store starts a fresh history), the preemption
        requeue/coverage checks, the job-completion expectations, and
        the opt-in placement-quality bound."""
        for c in self._update_checkers():
            c.finalize()
        pre_checkers = [entry[3] for entry in self._inv.values()]
        for c in pre_checkers:
            c.finalize()
        if self.expect_preemptions:
            seen = len(self._preempt_archive) + max(
                (c.seen_preemptions for c in pre_checkers), default=0)
            if not seen:
                violations.record(
                    "preemptions-observed",
                    "scenario expected priority preemption to fire but "
                    "no preemption marker was ever committed")
        if self.job_expectations and self.store is not None:
            tasks = self.store.view(lambda tx: tx.find(Task))
            for sid, total in self.job_expectations:
                done = sum(1 for t in tasks
                           if t.service_id == sid and t.status.state
                           == int(TaskState.COMPLETE))
                if done < total:
                    violations.record(
                        "job-completions-converge",
                        f"job {sid}: {done}/{total} completions after "
                        "heal+grace — job iterations lost across "
                        "failover")
        # ---- gang & pipeline end checks (ISSUE 16)
        if self.service_expectations and self.store is not None:
            tasks = self.store.view(lambda tx: tx.find(Task))
            for sid, want, label in self.service_expectations:
                running = sum(
                    1 for t in tasks
                    if t.service_id == sid
                    and TaskState(t.status.state) == TaskState.RUNNING
                    and t.desired_state <= TaskState.RUNNING)
                if running < want:
                    violations.record(
                        label,
                        f"service {sid}: {running}/{want} tasks RUNNING "
                        "after heal+grace — the gang/stage never "
                        "converged")
        if self.pipeline_expectations and self.store is not None:
            svc_rows = {s.id: s for s in self.store.view(
                lambda tx: tx.find(Service))}
            for sid, want_state, label in self.pipeline_expectations:
                s = svc_rows.get(sid)
                st = s.pipeline_status if s is not None else None
                got = st.state if st is not None else "waiting"
                if got != want_state:
                    reason = (f" (reason: {st.reason})"
                              if st is not None and st.reason else "")
                    violations.record(
                        label,
                        f"pipeline stage {sid}: verdict {got!r} at "
                        f"finish, expected {want_state!r}{reason}")
        history = self.merged_update_history()
        for version, states, by, label in self.update_expectations:
            hit = [h for h in history
                   if h[2] == version and h[3] in states and h[0] <= by]
            if not hit:
                seen = sorted({(h[2], h[3]) for h in history})
                violations.record(
                    label,
                    f"version {version} never reached states {sorted(states)} "
                    f"by t={by:.1f} (observed (version,state) pairs: "
                    f"{seen})")
        if self.placement_quality_bound is not None \
                and self.store is not None:
            check_placement_quality(violations, self.store,
                                    self.placement_quality_bound)
        # ---- autoscaler + QoS end checks
        for c in self._qos_checkers():
            c.drain()
        history = self.merged_replica_history()
        final_replicas: Dict[str, int] = {}
        if self.store is not None:
            for s in self.store.view(lambda tx: tx.find(Service)):
                if s.spec.replicated is not None:
                    final_replicas[s.id] = s.spec.replicated.replicas
        for kind, sid, replicas, by, label in self.autoscale_expectations:
            if kind == "reach":
                hit = [h for h in history
                       if h[1] == sid and h[2] >= replicas
                       and h[0] <= by]
                if not hit:
                    seen = [h[2] for h in history if h[1] == sid]
                    violations.record(
                        label,
                        f"service {sid} never reached {replicas} "
                        f"replicas by t={by:.1f} (observed {seen}) — "
                        "the scale-up was lost (failover?)")
            else:   # converge
                hit = [h for h in history
                       if h[1] == sid and h[2] == replicas
                       and h[0] <= by]
                if not hit or final_replicas.get(sid) != replicas:
                    violations.record(
                        label,
                        f"service {sid}: load removed but replicas "
                        f"never settled back at {replicas} by "
                        f"t={by:.1f} (final "
                        f"{final_replicas.get(sid)}) — the autoscaler "
                        "failed to converge")
        if self.band_p99_expectations:
            qos = next(iter(self._qos_checkers()), None)
            if qos is not None:
                samples, open_pending = self._merged_band_data()
                for min_prio, t0, t1 in self.band_p99_expectations:
                    qos.check_band_p99(
                        min_prio, t0, t1, violations,
                        samples=samples,
                        open_pending=[(p, since)
                                      for p, since in open_pending])
        # ---- read-plane end checks
        for w in self.watchers:
            w.drain()                 # catch up after the heal grace
            w.continuity.ensure()
            w.continuity.drain()
            w.continuity.judge(w)
        if self.watchers and not any(w.events_seen for w in self.watchers):
            violations.record(
                "watch-resume-no-gap-no-dup",
                "watchers attached but consumed zero events — the "
                "follower-served watch plane never carried the workload")
        if self.expect_reads_never_fail \
                and self.read_stats["probe_unavailable"]:
            violations.record(
                "read-storm-degraded",
                f"{self.read_stats['probe_unavailable']} linearizable "
                "read probe(s) failed outright under churn — reads must "
                "degrade to read-index latency, never to errors")
        # ---- overload-plane end checks (ISSUE 20): every client-observed
        # shed is dispatcher-counted, every shed task recovered, and no
        # node expired inside its promised heartbeat window
        self.overload_inv.finalize()


class Sim:
    """Top-level harness: engine + consensus layer + control plane +
    invariant sinks.  Use as a context manager (installs the virtual
    clock into models.types.now() and the deterministic id source, and
    restores both afterwards)."""

    def __init__(self, seed: int, n_managers: int = 3, n_agents: int = 5,
                 net_config: Optional[NetConfig] = None,
                 raft_cp: bool = False):
        """``raft_cp=True`` runs the raft-attached control plane
        (``RaftControlPlane``): per-member replicated stores, leader-only
        loops, epoch-fenced proposals.  False keeps the original
        standalone control-plane store alongside the consensus layer."""
        self.seed = seed
        self.engine = SimEngine(seed)
        # the virtual clock must be live BEFORE any component exists:
        # the dispatcher stamps registration-grace deadlines at run()
        # time, and a wall-clock value leaking into the deadline heap
        # would both break determinism and park those deadlines decades
        # past virtual time.  __exit__ restores the real clock.
        self.engine.clock.install()
        # deterministic ids for everything minted during the run —
        # session ids, orchestrator-created tasks — so event order (and
        # the flight-recorder dump) is a pure function of the seed
        self._id_seq = 0
        set_id_source(self._next_id)
        self.violations = Violations(self.engine)
        self.net = SimNetwork(self.engine, net_config)
        self.raft_inv = RaftInvariants(self.violations)
        member_ids = [f"m{i}" for i in range(n_managers)]
        self.finishing = False
        self.managers = [
            SimManager(mid, member_ids, self.engine, self.net,
                       self.raft_inv, with_store=raft_cp)
            for mid in member_ids]
        if raft_cp:
            self.cp = RaftControlPlane(self.engine, self.violations,
                                       self, n_agents)
        else:
            self.cp = SimControlPlane(self.engine, self.violations,
                                      n_agents)
        self.proposed = 0
        self.committed_target = 0

    def _next_id(self) -> str:
        self._id_seq += 1
        return f"sim{self.seed & 0xFFFFFFFF:08x}{self._id_seq:014d}"

    # ---------------------------------------------------------------- clock

    def __enter__(self) -> "Sim":
        self.engine.clock.install()     # idempotent
        set_id_source(self._next_id)
        return self

    def __exit__(self, *exc) -> None:
        self.engine.clock.uninstall()
        set_id_source(None)

    # ---------------------------------------------------------------- raft

    def leader(self) -> Optional[SimManager]:
        for m in self.managers:
            if m.alive and m.core.role == LEADER and m.core.leader_ready:
                return m
        return None

    def propose(self, payload: bytes) -> bool:
        m = self.leader()
        if m is None:
            return False
        m.core.propose(payload)
        m.pump()
        self.proposed += 1
        return True

    def start_raft_workload(self, interval: float = 0.4) -> None:
        def work():
            if self.finishing:
                return False
            self.propose(f"op-{self.proposed:05d}".encode())
            return None
        self.engine.every(interval, "raft workload", work)

    def stepdown_leader(self) -> None:
        m = self.leader()
        if m is not None:
            self.engine.log(f"fault stepdown {m.id}")
            m.core.step_down()
            m.pump()

    # -------------------------------------------------------------- running

    def run(self, duration: float) -> None:
        self.engine.run_until(duration)

    def finish(self, grace: float = 20.0) -> None:
        """Heal every fault, give the cluster ``grace`` virtual seconds
        to converge, then run end-state checks."""
        self.finishing = True
        self.net.heal_all()
        # rollout-poison heals with every other fault: replacements of
        # the once-poisoned version may now start, so a paused update
        # settles instead of churning failed restarts through the grace
        getattr(self.cp, "poison_versions", set()).clear()
        getattr(self.cp, "poison_services", set()).clear()
        for m in self.managers:
            m.tick_scale = 1.0
            if not m.alive:
                m.restart()
        for a in self.cp.agents:
            a.rate_scale = 1.0
            a.fail_p = 0.0
            a.partition(False)
            a.restart()
        self.engine.run_until(self.engine.clock.elapsed() + grace)
        self._check_convergence()
        self.cp.stopped = True
        for m in self.managers:
            m.stopped = True

    def _check_convergence(self) -> None:
        target = self.raft_inv.max_committed()
        for m in self.managers:
            if not m.alive:
                continue
            if m.core.applied_index < target:
                self.violations.record(
                    "post-heal-convergence",
                    f"{m.id} applied only {m.core.applied_index} of "
                    f"{target} committed entries after heal+grace")
        terms = {m.core.term for m in self.managers if m.alive}
        if len(terms) > 1:
            self.violations.record(
                "post-heal-convergence",
                f"terms did not converge after heal+grace: {sorted(terms)}")
        if isinstance(self.cp, RaftControlPlane):
            # failover re-placement: after every fault is healed and the
            # grace ran, the successor's control plane must have placed
            # every live task — a PENDING unplaced task means the
            # hand-off lost work
            store = self.cp.store
            if store is not None:
                tasks, services = store.view(
                    lambda tx: (tx.find(Task), tx.find(Service)))
                # pipeline-gated stages are intentionally unplaced: a
                # halted (or never-released) stage's pending tasks are
                # the DAG gate working, not lost work
                gated = set()
                for s in services:
                    if s.spec.depends_on:
                        st = s.pipeline_status
                        if st is None or st.state != "released":
                            gated.add(s.id)
                stuck = [
                    t for t in tasks
                    if t.desired_state == TaskState.RUNNING
                    and TaskState(t.status.state) == TaskState.PENDING
                    and not t.node_id
                    and t.service_id not in gated]
                if stuck:
                    self.violations.record(
                        "failover-replacement",
                        f"{len(stuck)} tasks still unplaced after "
                        "heal+grace")
            self.cp.check_end_state(self.violations)

    # ----------------------------------------------------------------- stats

    def stats(self) -> Dict[str, object]:
        store = self.cp.store
        tasks = store.view(lambda tx: tx.find(Task)) \
            if store is not None else []
        by_state: Dict[str, int] = {}
        for t in tasks:
            k = TaskState(t.status.state).name
            by_state[k] = by_state.get(k, 0) + 1
        if isinstance(self.cp, RaftControlPlane):
            disp = self.cp.dispatcher_stats
        else:
            disp = self.cp.dispatcher.stats
        out = {
            "events": self.engine.events_run,
            "net": dict(self.net.stats),
            "raft": {
                "proposed": self.proposed,
                "max_committed": self.raft_inv.max_committed(),
                "terms_seen": len(self.raft_inv.leaders),
                "restarts": sum(m.restarts for m in self.managers),
            },
            "tasks": by_state,
            "heartbeats": disp.get("heartbeats", 0),
            "expirations": disp.get("expirations", 0),
        }
        if isinstance(self.cp, RaftControlPlane):
            from ..models.types import UpdateState
            states = sorted({UpdateState(h[3]).name
                             for h in self.cp.merged_update_history()
                             if h[3] >= 0})
            out["control"] = {
                "attaches": self.cp.attaches,
                "quota_clamps": self.cp.quota_clamps(),
                "autoscale_changes": len(
                    self.cp.merged_replica_history()),
                "stale_epoch_rejects": sum(
                    p.stats["stale_epoch_rejects"]
                    for p in self.cp.proposers.values()),
                "proposed": sum(p.stats["proposed"]
                                for p in self.cp.proposers.values()),
                "committed": sum(p.stats["committed"]
                                 for p in self.cp.proposers.values()),
                "rollouts": self.cp.rollouts,
                "update_states": states,
            }
            reads = dict(self.cp.read_stats)
            for k in ("reads", "lease", "read_index", "unavailable"):
                reads[k] = sum(p.read_stats[k]
                               for p in self.cp.proposers.values())
            reads["leader_share"] = round(
                self.cp.leader_read_share(), 4)
            reads["watch_events"] = sum(
                w.events_seen for w in self.cp.watchers)
            reads["watch_hops"] = sum(
                w.hops for w in self.cp.watchers)
            out["reads"] = reads
            out["overload"] = {
                "sheds": disp.get("sheds", 0),
                "client_sheds": self.cp.overload_inv.client_sheds,
                "shed_tasks": len(self.cp.overload_inv.shed_tasks),
                "hb_stretches": disp.get("hb_stretches", 0),
                "premature_expirations": disp.get(
                    "premature_expirations", 0),
            }
            fleets = [a._fleet for a in self.cp.agents
                      if isinstance(a, _MuxAgent)]
            if fleets:
                fleet = fleets[0]
                out["fleet"] = dict(fleet.stats)
                out["fleet"]["sessions"] = len(fleet.agents)
        return out
