"""SimCluster: an in-process multi-manager / multi-agent cluster driven
entirely by the simulation engine.

Two layers share one event loop, one virtual clock, and one seeded RNG:

* **Consensus layer** — N raft members built on the real ``RaftCore``
  (the same sans-IO state machine production uses) with an in-memory
  WAL that models durability faithfully: every Ready's hard state and
  entries persist BEFORE messages send, a crash loses all volatile
  state but keeps the WAL, and a crash-with-truncation loses the last
  k WAL records ("died before fsync").  Messages route through
  ``SimNetwork`` with seeded delay/drop/duplication and partitions.

* **Control-plane layer** — the real ``Scheduler`` and ``Dispatcher``
  running single-threaded against a leader store under virtual time
  (the dispatcher's worker thread is replaced by direct
  ``process_deadlines`` calls; the scheduler's event loop by explicit
  resync+tick steps), plus simulated agents that register, heartbeat,
  advance task FSMs, and fail on command.  In this subsystem version
  the control-plane store is standalone (not raft-attached); committed
  raft entries and store commits are invariant-checked independently.

Determinism contract: all object ids the simulation creates are
deterministic strings, every random draw comes from the engine's seeded
RNG tree, and RaftCore broadcasts iterate peers in sorted order — so a
run's trace hash is a pure function of (scenario, seed).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..manager.dispatcher import Config_ as DispatcherConfig, Dispatcher, \
    DispatcherError
from ..models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    ReplicatedService, Resources, Service, ServiceMode, ServiceSpec, Task,
    TaskSpec, TaskState, TaskStatus, Version,
)
from ..models.types import TERMINAL_STATES, now
from ..scheduler import Scheduler
from ..scheduler.filters import VolumesFilter
from ..state.raft.core import (
    ENTRY_CONF, Entry, HardState, LEADER, RaftCore,
)
from ..state.store import MemoryStore
from .engine import SimEngine
from .faults import NetConfig, SimNetwork
from .invariants import (
    RaftInvariants, TaskInvariants, Violations, entry_digest,
)


class SimManager:
    """One raft member with an in-memory durable WAL."""

    TICK = 0.1   # seconds of virtual time per raft tick

    def __init__(self, member_id: str, peers: List[str], engine: SimEngine,
                 net: SimNetwork, raft_inv: RaftInvariants):
        self.id = member_id
        self.peers = list(peers)
        self.engine = engine
        self.net = net
        self.raft_inv = raft_inv
        self.alive = True
        self.stopped = False
        self.tick_scale = 1.0    # clock-skew fault: >1 ticks slower
        # durable state ("disk"): survives crashes, lost records only
        # through explicit truncation faults
        self._wal_records: List[tuple] = []   # ("hs", HardState)|("ent", Entry)
        # apply tap for data entries: (member_id, entry) per applied
        # non-conf entry — SimRaftProposer completes its waiters (and
        # runs store commit callbacks in the apply path) through this,
        # mirroring RaftNode._apply_entry's waiter handling
        self.on_apply = None
        self.restarts = 0
        self.core = self._new_core()
        net.register(member_id, self._on_message)
        self._schedule_tick()

    def _new_core(self) -> RaftCore:
        core = RaftCore(self.id, self.peers, rng=self.engine.fork_rng(),
                        prevote=True)
        # role transitions land in the flight recorder under virtual
        # time — part of the deterministic post-mortem a failing seed
        # dumps (scenario.run_scenario)
        from ..obs.flightrec import flightrec
        core.on_transition = flightrec.record_raft
        return core

    # ------------------------------------------------------------ event loop

    def _schedule_tick(self) -> None:
        def loop():
            if self.stopped:
                return
            if self.alive:
                self.core.tick()
                self.pump()
            self.engine.after(self.TICK * self.tick_scale,
                              f"{self.id} tick", loop)
        self.engine.after(self.TICK * self.tick_scale,
                          f"{self.id} tick", loop)

    def _on_message(self, msg) -> None:
        if not self.alive:
            return
        self.core.step(msg)
        self.pump()

    def pump(self) -> None:
        """The Ready loop: persist -> send -> apply -> advance, exactly
        the ordering RaftNode uses (durability before visibility)."""
        while self.core.has_ready():
            rd = self.core.ready()
            if rd.hard_state is not None:
                self._wal_records.append(
                    ("hs", HardState(rd.hard_state.term,
                                     rd.hard_state.voted_for,
                                     rd.hard_state.commit)))
            for e in rd.entries:
                self._wal_records.append(
                    ("ent", Entry(e.term, e.index, e.data, e.type)))
            for m in rd.messages:
                self.net.send(m)
            for e in rd.committed:
                self._apply(e)
            self.core.advance(rd)
        if self.core.role == LEADER:
            self.raft_inv.observe_leader(self.core.term, self.id)

    def _apply(self, e: Entry) -> None:
        self.raft_inv.observe_apply(self.id, e.index, e.term,
                                    f"{e.type}:{entry_digest(e.data)}")
        if e.type == ENTRY_CONF:
            try:
                change = json.loads(e.data)
                self.core.apply_conf_change(change["op"], change["id"])
            except Exception:
                pass
            return
        if self.on_apply is not None and e.data:
            self.on_apply(self.id, e)

    # ---------------------------------------------------------------- faults

    def crash(self, truncate_wal: int = 0) -> None:
        """Lose all volatile state; optionally lose the last
        ``truncate_wal`` WAL records.

        Truncation models a crash BEFORE fsync — which is OUTSIDE raft's
        fault model: this member already acked those records, so the
        cluster may have counted it toward a commit majority.  Default
        scenarios and the fuzzer therefore crash with the WAL intact;
        truncation exists precisely so tests can inject a durability bug
        and prove the invariant checkers catch it (see
        tests/test_sim.py::test_checker_detects_seeded_durability_bug)."""
        if not self.alive:
            return
        self.alive = False
        if truncate_wal > 0:
            dropped = self._wal_records[-truncate_wal:]
            del self._wal_records[-truncate_wal:]
            self.engine.log(
                f"fault crash {self.id} truncate={len(dropped)}")
        else:
            self.engine.log(f"fault crash {self.id}")
        self.net.isolate(self.id)

    def restart(self) -> None:
        if self.alive:
            return
        self.restarts += 1
        hs, entries = self._replay_wal()
        self.core = self._new_core()
        self.core.load(hs, entries, None)
        # re-apply the committed prefix to the (new) state machine; the
        # invariant ledger cross-checks every re-applied entry
        for e in self.core.entries_from(1):
            if e.index > self.core.commit_index:
                break
            self._apply(e)
            self.core.applied_index = e.index
        self.alive = True
        self.net.rejoin(self.id)
        self.engine.log(f"fault restart {self.id} "
                        f"commit={self.core.commit_index}")

    def _replay_wal(self):
        """Mirror RaftLogger._load_wal: later entry records override
        earlier ones at the same or higher index (truncation)."""
        hs = HardState()
        entries: List[Entry] = []
        for kind, rec in self._wal_records:
            if kind == "hs":
                hs = HardState(rec.term, rec.voted_for, rec.commit)
            else:
                while entries and entries[-1].index >= rec.index:
                    entries.pop()
                entries.append(rec)
        # a truncated WAL may report a commit index beyond the surviving
        # entries; clamp like a real bootstrap would (can't commit what
        # is not on disk)
        last = entries[-1].index if entries else 0
        if hs.commit > last:
            hs = HardState(hs.term, hs.voted_for, last)
        return hs, entries


class SimAgent:
    """A worker: registers with the dispatcher, heartbeats, advances the
    task FSM one step per cycle, fails tasks on command."""

    FSM_NEXT = {
        TaskState.ASSIGNED: TaskState.ACCEPTED,
        TaskState.ACCEPTED: TaskState.PREPARING,
        TaskState.PREPARING: TaskState.READY,
        TaskState.READY: TaskState.STARTING,
        TaskState.STARTING: TaskState.RUNNING,
    }

    def __init__(self, node_id: str, cp: "SimControlPlane",
                 interval: float = 1.0):
        self.node_id = node_id
        self.cp = cp
        self.engine = cp.engine
        self.interval = interval
        self.rate_scale = 1.0      # clock-skew fault
        self.alive = True
        self.partitioned = False
        self.fail_p = 0.0          # per-step chance of failing a RUNNING task
        self.session: Optional[str] = None
        self._rng = cp.engine.fork_rng()
        self._schedule()

    def _schedule(self) -> None:
        def loop():
            if self.cp.stopped:
                return
            self.step()
            self.engine.after(self.interval * self.rate_scale,
                              f"agent {self.node_id} step", loop)
        # deterministic phase offset so agents don't step in lockstep
        self.engine.after(self._rng.random() * self.interval,
                          f"agent {self.node_id} step", loop)

    # ------------------------------------------------------------------ step

    def step(self) -> None:
        if not self.alive or self.partitioned:
            return
        d = self.cp.dispatcher
        try:
            if self.session is None:
                self.session, _ = d.register(
                    self.node_id,
                    description=NodeDescription(hostname=self.node_id))
                self.engine.log(f"agent {self.node_id} registered")
            else:
                d.heartbeat(self.node_id, self.session)
        except DispatcherError:
            self.session = None
            return
        self._advance_tasks()

    def _advance_tasks(self) -> None:
        from ..state.store import ByNode
        tasks = self.cp.store.view(
            lambda tx: tx.find(Task, ByNode(self.node_id)))
        updates = []
        for t in sorted(tasks, key=lambda t: t.id):
            state = TaskState(t.status.state)
            if state in TERMINAL_STATES:
                continue
            if t.desired_state >= TaskState.SHUTDOWN:
                updates.append((t.id, TaskStatus(
                    state=TaskState.SHUTDOWN, timestamp=now(),
                    message="sim shutdown")))
                continue
            if state == TaskState.RUNNING:
                if self.fail_p and self._rng.random() < self.fail_p:
                    updates.append((t.id, TaskStatus(
                        state=TaskState.FAILED, timestamp=now(),
                        message="sim fault", err="injected failure")))
                    self.engine.log(f"agent {self.node_id} failed task "
                                    f"{t.id}")
                continue
            nxt = self.FSM_NEXT.get(state)
            if nxt is not None:
                updates.append((t.id, TaskStatus(
                    state=nxt, timestamp=now(), message="sim")))
        if updates:
            try:
                self.cp.dispatcher.update_task_status(
                    self.node_id, self.session, updates)
            except DispatcherError:
                self.session = None

    # ---------------------------------------------------------------- faults

    def crash(self) -> None:
        if self.alive:
            self.alive = False
            self.session = None
            self.engine.log(f"fault agent-crash {self.node_id}")

    def restart(self) -> None:
        if not self.alive:
            self.alive = True
            self.engine.log(f"fault agent-restart {self.node_id}")

    def partition(self, on: bool) -> None:
        self.partitioned = on
        self.engine.log(f"fault agent-partition {self.node_id} "
                        f"{'on' if on else 'off'}")


class SimRaftProposer:
    """MemoryStore ``Proposer`` backed by the sim's consensus layer:
    proposals ride the real RaftCore through SimNetwork faults, and
    commit callbacks run in the proposing member's apply path (the
    ``SimManager.on_apply`` tap), mirroring RaftNode's waiter handling.

    Implements the async pair (``propose_async``/``wait_proposal``) the
    store's chunk-pipelined block commit uses, so leader churn against
    in-flight pipelined proposals is simulatable deterministically.
    ``wait_proposal`` advances VIRTUAL time by pumping the engine, so it
    must only be driven from top-level scenario code — never from inside
    an engine event (the engine loop is not re-entrant).
    """

    PUMP = 0.05      # virtual seconds per wait slice
    TIMEOUT = 30.0   # virtual seconds before a proposal is abandoned

    def __init__(self, sim: "Sim"):
        self.sim = sim
        self._pending: Dict[tuple, dict] = {}
        self.stats = {"proposed": 0, "committed": 0, "dropped": 0}
        for m in sim.managers:
            m.on_apply = self._on_apply

    # ------------------------------------------------------------- proposer

    def propose_async(self, actions, commit_cb=None) -> dict:
        from ..state import serde
        leader = self.sim.leader()
        if leader is None:
            raise RuntimeError("no ready raft leader to propose to")
        data = serde.dumps([serde.action_to_dict(a) for a in actions])
        index = leader.core.propose(data)
        leader.pump()
        waiter = {"member": leader, "index": index,
                  "commit_cb": commit_cb, "done": False, "ok": False,
                  "deadline": self.sim.engine.clock.elapsed()
                  + self.TIMEOUT}
        self._pending[(leader.id, index)] = waiter
        self.stats["proposed"] += 1
        return waiter

    def wait_proposal(self, waiter: dict) -> None:
        from ..state.raft.node import ProposalDropped
        eng = self.sim.engine
        while not waiter["done"]:
            m = waiter["member"]
            if not m.alive or m.stopped:
                # the proposing member is gone: its store can never run
                # the commit callback, so the proposal fails here even
                # if the entry later commits cluster-wide (a real
                # manager rebuilds its store from the WAL on restart)
                self._fail(waiter)
                break
            if m.core.role != LEADER \
                    and m.core.commit_index < waiter["index"]:
                self._fail(waiter)   # deposed before the entry committed
                break
            if eng.clock.elapsed() >= waiter["deadline"]:
                self._fail(waiter)
                break
            eng.run_until(eng.clock.elapsed() + self.PUMP)
        if not waiter["ok"]:
            self.stats["dropped"] += 1
            raise ProposalDropped("sim raft proposal dropped")
        self.stats["committed"] += 1

    def propose(self, actions, commit_cb=None) -> None:
        self.wait_proposal(self.propose_async(actions, commit_cb))

    # ------------------------------------------------------------ apply tap

    def _on_apply(self, member_id: str, entry) -> None:
        waiter = self._pending.pop((member_id, entry.index), None)
        if waiter is None or waiter["done"]:
            return
        ok = True
        if waiter["commit_cb"] is not None:
            try:
                waiter["commit_cb"]()
            except Exception:
                ok = False
        waiter["ok"] = ok
        waiter["done"] = True

    def _fail(self, waiter: dict) -> None:
        self._pending.pop((waiter["member"].id, waiter["index"]), None)
        waiter["done"] = True
        waiter["ok"] = False


class SimControlPlane:
    """The leader's store + real Scheduler + real Dispatcher, driven
    synchronously under virtual time."""

    def __init__(self, engine: SimEngine, violations: Violations,
                 n_agents: int, control_interval: float = 0.5):
        self.engine = engine
        self.stopped = False
        self.store = MemoryStore()
        self.invariants = TaskInvariants(violations, self.store)
        self.dispatcher = Dispatcher(
            self.store,
            DispatcherConfig(heartbeat_period=2.0, heartbeat_epsilon=0.2,
                             grace_multiplier=3.0, rate_limit_period=0.0,
                             orphan_timeout=20.0),
            rng=engine.fork_rng())
        # pipeline_depth=1: the committer thread of the pipelined tick
        # would break the sim's single-threaded determinism contract;
        # chunk-pipelined PROPOSALS (store-level, single-threaded) are
        # exercised by the pipelined-commit-churn scenario instead
        self.scheduler = Scheduler(self.store, pipeline_depth=1)
        self.scheduler.pipeline.add_filter(
            VolumesFilter(self.scheduler.volumes))
        self._task_seq = 0
        self._replaced: set = set()
        self.service = Service(
            id="svc-sim",
            spec=ServiceSpec(
                annotations=Annotations(name="sim"),
                mode=ServiceMode.REPLICATED,
                replicated=ReplicatedService(replicas=0),
                task=TaskSpec()),
            spec_version=Version(index=1))
        self.store.update(lambda tx: tx.create(self.service))

        self.agents: List[SimAgent] = []
        for i in range(n_agents):
            node = Node(
                id=f"w{i}",
                spec=NodeSpec(annotations=Annotations(name=f"w{i}")),
                status=NodeStatus(state=NodeState.UNKNOWN),
                description=NodeDescription(
                    hostname=f"w{i}",
                    resources=Resources(nano_cpus=8 * 10 ** 9,
                                        memory_bytes=32 << 30)))
            self.store.update(lambda tx, n=node: tx.create(n))
            self.agents.append(SimAgent(f"w{i}", self))

        # dispatcher up, worker thread replaced by control_step
        self.dispatcher.run(start_worker=False)
        self.store.view(self.scheduler._setup_tasks_list)
        engine.every(control_interval, "control step", self.control_step)

    # -------------------------------------------------------------- workload

    def create_tasks(self, n: int) -> None:
        def cb(tx):
            for _ in range(n):
                self._task_seq += 1
                tx.create(Task(
                    id=f"t{self._task_seq:05d}",
                    service_id=self.service.id,
                    slot=self._task_seq,
                    desired_state=TaskState.RUNNING,
                    spec=self.service.spec.task,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        self.store.update(cb)
        self.engine.log(f"workload create {n} tasks")

    # ---------------------------------------------------------- control loop

    def control_step(self) -> object:
        if self.stopped:
            return False
        self.dispatcher.process_deadlines()
        self.dispatcher._flush_updates()
        self.scheduler._resync()
        n = self.scheduler.tick()
        if n:
            self.engine.log(f"scheduler assigned {n}")
        self._restart_step()
        self.invariants.drain()
        return None

    def _restart_step(self) -> None:
        """Minimal orchestrator stand-in: replace terminal tasks whose
        desired state is still RUNNING (new task id, same slot — the
        restart supervisor's contract; the full orchestrators are
        exercised separately by the block-contract tests)."""
        tasks = self.store.view(lambda tx: tx.find(Task))
        to_replace = [
            t for t in sorted(tasks, key=lambda t: t.id)
            if TaskState(t.status.state) in TERMINAL_STATES
            and t.desired_state == TaskState.RUNNING
            and t.id not in self._replaced]
        if not to_replace:
            return

        def cb(tx):
            for t in to_replace:
                self._replaced.add(t.id)
                cur = tx.get(Task, t.id)
                if cur is not None:
                    cur = cur.copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    tx.update(cur)
                self._task_seq += 1
                tx.create(Task(
                    id=f"t{self._task_seq:05d}",
                    service_id=self.service.id,
                    slot=t.slot,
                    desired_state=TaskState.RUNNING,
                    spec=self.service.spec.task,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        self.store.update(cb)
        self.engine.log(f"restart replaced {len(to_replace)}")


class Sim:
    """Top-level harness: engine + consensus layer + control plane +
    invariant sinks.  Use as a context manager (installs the virtual
    clock into models.types.now() and restores it afterwards)."""

    def __init__(self, seed: int, n_managers: int = 3, n_agents: int = 5,
                 net_config: Optional[NetConfig] = None):
        self.seed = seed
        self.engine = SimEngine(seed)
        # the virtual clock must be live BEFORE any component exists:
        # the dispatcher stamps registration-grace deadlines at run()
        # time, and a wall-clock value leaking into the deadline heap
        # would both break determinism and park those deadlines decades
        # past virtual time.  __exit__ restores the real clock.
        self.engine.clock.install()
        self.violations = Violations(self.engine)
        self.net = SimNetwork(self.engine, net_config)
        self.raft_inv = RaftInvariants(self.violations)
        member_ids = [f"m{i}" for i in range(n_managers)]
        self.finishing = False
        self.managers = [
            SimManager(mid, member_ids, self.engine, self.net,
                       self.raft_inv)
            for mid in member_ids]
        self.cp = SimControlPlane(self.engine, self.violations, n_agents)
        self.proposed = 0
        self.committed_target = 0

    # ---------------------------------------------------------------- clock

    def __enter__(self) -> "Sim":
        self.engine.clock.install()     # idempotent
        return self

    def __exit__(self, *exc) -> None:
        self.engine.clock.uninstall()

    # ---------------------------------------------------------------- raft

    def leader(self) -> Optional[SimManager]:
        for m in self.managers:
            if m.alive and m.core.role == LEADER and m.core.leader_ready:
                return m
        return None

    def propose(self, payload: bytes) -> bool:
        m = self.leader()
        if m is None:
            return False
        m.core.propose(payload)
        m.pump()
        self.proposed += 1
        return True

    def start_raft_workload(self, interval: float = 0.4) -> None:
        def work():
            if self.finishing:
                return False
            self.propose(f"op-{self.proposed:05d}".encode())
            return None
        self.engine.every(interval, "raft workload", work)

    def stepdown_leader(self) -> None:
        m = self.leader()
        if m is not None:
            self.engine.log(f"fault stepdown {m.id}")
            m.core.step_down()
            m.pump()

    # -------------------------------------------------------------- running

    def run(self, duration: float) -> None:
        self.engine.run_until(duration)

    def finish(self, grace: float = 20.0) -> None:
        """Heal every fault, give the cluster ``grace`` virtual seconds
        to converge, then run end-state checks."""
        self.finishing = True
        self.net.heal_all()
        for m in self.managers:
            m.tick_scale = 1.0
            if not m.alive:
                m.restart()
        for a in self.cp.agents:
            a.rate_scale = 1.0
            a.fail_p = 0.0
            a.partition(False)
            a.restart()
        self.engine.run_until(self.engine.clock.elapsed() + grace)
        self._check_convergence()
        self.cp.stopped = True
        for m in self.managers:
            m.stopped = True

    def _check_convergence(self) -> None:
        target = self.raft_inv.max_committed()
        for m in self.managers:
            if not m.alive:
                continue
            if m.core.applied_index < target:
                self.violations.record(
                    "post-heal-convergence",
                    f"{m.id} applied only {m.core.applied_index} of "
                    f"{target} committed entries after heal+grace")
        terms = {m.core.term for m in self.managers if m.alive}
        if len(terms) > 1:
            self.violations.record(
                "post-heal-convergence",
                f"terms did not converge after heal+grace: {sorted(terms)}")

    # ----------------------------------------------------------------- stats

    def stats(self) -> Dict[str, object]:
        tasks = self.cp.store.view(lambda tx: tx.find(Task))
        by_state: Dict[str, int] = {}
        for t in tasks:
            k = TaskState(t.status.state).name
            by_state[k] = by_state.get(k, 0) + 1
        return {
            "events": self.engine.events_run,
            "net": dict(self.net.stats),
            "raft": {
                "proposed": self.proposed,
                "max_committed": self.raft_inv.max_committed(),
                "terms_seen": len(self.raft_inv.leaders),
                "restarts": sum(m.restarts for m in self.managers),
            },
            "tasks": by_state,
            "heartbeats": self.cp.dispatcher.stats["heartbeats"],
            "expirations": self.cp.dispatcher.stats["expirations"],
        }
