"""Seeded single-threaded event loop with trace recording.

Everything in a simulation — raft ticks, message deliveries, agent
heartbeats, control-plane steps, fault injections — is an event on one
heap ordered by (virtual time, sequence number).  Sequence numbers break
ties deterministically, and the only randomness anywhere is
``engine.rng`` (or generators seeded from it), so a run is a pure
function of its seed.  The trace records every event execution; its
SHA-256 is the run's identity — two runs with the same seed must produce
the same hash, byte for byte.
"""

from __future__ import annotations

import hashlib
import heapq
import random
from typing import Callable, List

from .clock import VirtualClock


class SimEngine:
    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)
        self.clock = VirtualClock()
        self._heap: list = []        # (time, seq, label, fn)
        self._seq = 0
        self._cancelled: set = set()
        self.trace: List[str] = []
        self.events_run = 0
        self.max_events = 2_000_000  # runaway backstop
        # member ids currently under a clock-skew fault (SimManager's
        # tick_scale setter maintains it).  Shared on the engine because
        # skew ANYWHERE voids every leader's lease math — the read plane
        # checks this set before honoring a lease read.
        self.clock_skew_members: set = set()

    # ------------------------------------------------------------ scheduling

    def at(self, t: float, label: str, fn: Callable[[], None]) -> int:
        """Schedule ``fn`` at absolute virtual time ``t``; returns an id
        usable with cancel()."""
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, label, fn))
        return self._seq

    def after(self, dt: float, label: str, fn: Callable[[], None]) -> int:
        return self.at(self.clock.time() + max(0.0, dt), label, fn)

    def every(self, interval: float, label: str,
              fn: Callable[[], object], phase: float = 0.0) -> None:
        """Repeating event.  ``fn`` returning False stops the series."""

        def run():
            if fn() is False:
                return
            self.after(interval, label, run)

        self.after(phase if phase > 0 else interval, label, run)

    def cancel(self, event_id: int) -> None:
        self._cancelled.add(event_id)

    # --------------------------------------------------------------- running

    def run_until(self, t_end: float) -> None:
        """Pop events in order until virtual time reaches ``t_end``.

        Re-entrant: an event handler may itself call ``run_until`` (the
        raft-attached control plane blocks on consensus by pumping
        virtual time from inside a control step — see
        ``SimRaftProposer.wait_proposal``).  The inner call consumes
        heap events up to ITS deadline; the outer loop simply finds them
        gone.  Still single-threaded and heap-ordered, so determinism is
        untouched — only the clock clamp below is needed, because an
        inner pump may have advanced time past the outer deadline."""
        end = self.clock.start + t_end
        while self._heap and self._heap[0][0] <= end:
            t, seq, label, fn = heapq.heappop(self._heap)
            if seq in self._cancelled:
                self._cancelled.discard(seq)
                continue
            self.clock.advance_to(max(t, self.clock.time()))
            self.events_run += 1
            if self.events_run > self.max_events:
                raise RuntimeError("simulation exceeded max_events")
            fn()
        self.clock.advance_to(max(end, self.clock.time()))

    # ----------------------------------------------------------------- trace

    def log(self, msg: str) -> None:
        self.trace.append(f"{self.clock.elapsed():.6f} {msg}")

    def trace_hash(self) -> str:
        h = hashlib.sha256()
        for line in self.trace:
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()

    def fork_rng(self) -> random.Random:
        """A child RNG seeded from the engine stream: components that
        consume randomness at their own cadence (raft election jitter,
        per-agent failure draws) get independent deterministic streams."""
        return random.Random(self.rng.getrandbits(64))
