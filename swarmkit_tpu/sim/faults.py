"""Simulated network + the fault vocabulary.

``SimNetwork`` implements the same two-method transport surface as
``state.raft.transport.LocalNetwork`` (register/send) but routes every
message through the engine's event heap with seeded delay, drop,
duplication, and jitter (jitter IS reordering: two messages on the same
link can land out of order).  Partitions are modeled as link predicates:
symmetric (node isolated both ways), asymmetric (one direction only),
and group partitions (the classic split-brain shape).

The fault taxonomy here is what both scripted scenarios and the fuzzer
compose:

* message faults — drop, delay burst, duplicate, reorder (jitter)
* partitions    — isolate(node), cut(a,b), split(groups), asymmetric
* process faults — crash (volatile state lost, WAL kept), restart,
  crash with WAL tail truncation ("died before fsync")
* timing faults — clock skew as per-component tick-rate multipliers
* leadership    — forced step-down (leader churn)
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..state.raft.core import Message


class NetConfig:
    """Steady-state link behavior (before injected faults)."""

    def __init__(self, base_delay: float = 0.005, jitter: float = 0.005,
                 drop_p: float = 0.0, dup_p: float = 0.0):
        self.base_delay = base_delay
        self.jitter = jitter
        self.drop_p = drop_p
        self.dup_p = dup_p


class SimNetwork:
    """Engine-driven message router with fault injection."""

    def __init__(self, engine, config: Optional[NetConfig] = None):
        self.engine = engine
        self.config = config or NetConfig()
        self._rng = engine.fork_rng()
        self._handlers: Dict[str, Callable[[Message], None]] = {}
        self._isolated: Set[str] = set()
        self._cut: Set[Tuple[str, str]] = set()      # directed
        self._groups: Optional[List[Set[str]]] = None
        self.stats = {"sent": 0, "delivered": 0, "dropped": 0,
                      "duplicated": 0}

    # --------------------------------------------------- transport surface

    def register(self, node_id: str,
                 handler: Callable[[Message], None]) -> None:
        self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        self._handlers.pop(node_id, None)

    def send(self, msg: Message) -> None:
        self.stats["sent"] += 1
        if not self._link_up(msg.src, msg.dst):
            self.stats["dropped"] += 1
            return
        if self.config.drop_p and self._rng.random() < self.config.drop_p:
            self.stats["dropped"] += 1
            self.engine.log(f"net drop {msg.src}->{msg.dst} {msg.type}")
            return
        copies = 1
        if self.config.dup_p and self._rng.random() < self.config.dup_p:
            copies = 2
            self.stats["duplicated"] += 1
        for _ in range(copies):
            delay = self.config.base_delay + \
                self._rng.random() * self.config.jitter
            self.engine.after(
                delay, f"deliver {msg.src}->{msg.dst} {msg.type}",
                lambda m=msg: self._deliver(m))

    def _deliver(self, msg: Message) -> None:
        # partition state is re-checked at DELIVERY time: a message in
        # flight when the partition lands is lost, like a real cut
        if not self._link_up(msg.src, msg.dst):
            self.stats["dropped"] += 1
            return
        handler = self._handlers.get(msg.dst)
        if handler is None:
            self.stats["dropped"] += 1
            return
        self.stats["delivered"] += 1
        handler(msg)

    # ------------------------------------------------------------- topology

    def _link_up(self, src: str, dst: str) -> bool:
        if src in self._isolated or dst in self._isolated:
            return False
        if (src, dst) in self._cut:
            return False
        if self._groups is not None:
            for g in self._groups:
                if src in g:
                    return dst in g
            return False   # src in no group: fully dark
        return True

    def isolate(self, node_id: str) -> None:
        """Symmetric partition of one node."""
        self._isolated.add(node_id)
        self.engine.log(f"fault isolate {node_id}")

    def rejoin(self, node_id: str) -> None:
        self._isolated.discard(node_id)
        self.engine.log(f"fault rejoin {node_id}")

    def cut(self, a: str, b: str, symmetric: bool = True) -> None:
        """Sever a link; ``symmetric=False`` gives an asymmetric
        partition (a can reach b, b cannot reach a is expressed as
        cut(b, a, symmetric=False))."""
        self._cut.add((a, b))
        if symmetric:
            self._cut.add((b, a))
        self.engine.log(f"fault cut {a}<->{b}" if symmetric
                        else f"fault cut {a}->{b}")

    def heal(self, a: str, b: str) -> None:
        self._cut.discard((a, b))
        self._cut.discard((b, a))
        self.engine.log(f"fault heal {a}<->{b}")

    def split(self, *groups: List[str]) -> None:
        """Partition the network into the given groups (nodes absent
        from every group go fully dark)."""
        self._groups = [set(g) for g in groups]
        self.engine.log(
            "fault split " + " | ".join(",".join(sorted(g))
                                        for g in self._groups))

    def heal_all(self) -> None:
        self._groups = None
        self._cut.clear()
        self._isolated.clear()
        self.engine.log("fault heal-all")
