"""Randomized interleaving fuzzer.

Each seed drives the ``random-fuzz`` scenario: the entire fault
timeline — partitions, crashes (clean and truncated-WAL), leader churn,
drop bursts, agent faults, clock skew — is drawn deterministically from
that seed.  A failing seed therefore IS the counterexample: re-running
it reproduces the identical event trace byte-for-byte
(``python -m swarmkit_tpu.sim --seed N --scenario random-fuzz``).
"""

from __future__ import annotations

from typing import List, Optional

from .scenario import FUZZ_POOL, SimReport, run_scenario


def fuzz(n_seeds: int, start_seed: int = 0,
         scenario: Optional[str] = "random-fuzz",
         progress=None) -> List[SimReport]:
    """Run ``n_seeds`` seeded simulations; returns every report (check
    ``.ok`` / ``.violations``).

    ``scenario=None`` rotates seeds through the whole registry pool
    (``scenario.FUZZ_POOL`` — every scenario except the documented
    exclusions, raft_cp rollout suite and legacy-rcp variants included),
    so fuzz coverage tracks the registry instead of silently lagging it;
    seed ``i`` runs ``FUZZ_POOL[i % len(FUZZ_POOL)]``, keeping each
    (scenario, seed) pair reproducible from the report alone."""
    reports = []
    for seed in range(start_seed, start_seed + n_seeds):
        name = scenario if scenario is not None \
            else FUZZ_POOL[seed % len(FUZZ_POOL)]
        report = run_scenario(name, seed)
        reports.append(report)
        if progress is not None:
            progress(report)
    return reports


def failures(reports: List[SimReport]) -> List[SimReport]:
    return [r for r in reports if not r.ok]


def pool_scenario(seed: int) -> str:
    """The scenario a pool-rotating fuzz run gives ``seed``."""
    return FUZZ_POOL[seed % len(FUZZ_POOL)]


def reproduce(seed: int, scenario: str = "random-fuzz",
              expect_hash: Optional[str] = None) -> SimReport:
    """Replay one seed; optionally assert the trace hash matches the
    original run (the determinism guarantee the whole subsystem rests
    on)."""
    report = run_scenario(scenario, seed)
    if expect_hash is not None and report.trace_hash != expect_hash:
        raise AssertionError(
            f"nondeterministic replay: trace hash {report.trace_hash} "
            f"!= expected {expect_hash}")
    return report
