"""Safety invariants checked continuously during simulation.

Raft layer (checked on every state change, cluster-wide):

* single-leader-per-term — two members must never both be LEADER in the
  same term
* committed-entry agreement / no loss — once ANY member applies entry
  (index, term, digest), every member that ever applies that index must
  apply the identical entry, including after crash/restore from WAL

Control-plane layer (checked against the leader store's event stream):

* task FSM never moves backwards — observed status.state is monotone
  per task; desired_state is monotone per task
* terminal states are sticky — a COMPLETE/FAILED/... task never leaves
  the terminal set
* assignment liveness — when a task reaches ASSIGNED, its node exists
  and is not DOWN in the same store view
* no double assignment — a task's node_id never changes once set
* blocks are never failures — EventTaskBlock only ever carries
  assignment-band states (<= RUNNING), by contract

Rolling-update layer (``UpdateInvariants``, stream-ordered like
``TaskInvariants``; quality-not-just-safety framing per PAPERS.md
2302.05446 — the control plane must bound convergence and placement
quality under perturbation, not merely avoid unsafe states):

* no-mixed-version-after-completion — once an update reports COMPLETED
  (and a short settle absorbs racing restarts), every task slated to
  keep running carries the completed spec version
* rollback-restores-old-spec-everywhere — the same check at
  ROLLBACK_COMPLETED against the restored version
* pause-on-failure-threshold — a paused update must stop claiming new
  slots for the paused version
* update-convergence-within-bound — scenario-registered expectations
  (``RaftControlPlane.expect_update``) judged against the observed
  update-state history at finish
* placement-quality (``check_placement_quality``) — post-convergence,
  running tasks may not pile onto one node beyond a bound of the ideal
  even spread

Gang/pipeline layer (ISSUE 16; ``GangInvariants`` /
``PipelineInvariants``, payload discipline like ``TaskInvariants``
plus commit boundaries from ``EventCommit``):

* gang-atomicity — no committed transaction may assign a strict subset
  of a gang unit's pending members; judged at each ``EventCommit``
  with a short grace window so concurrent orchestrator churn (a
  replacement materializing between the scheduler's snapshot and its
  commit) resolves instead of flagging
* pipeline-order — a task of a ``depends_on`` stage must never reach
  RUNNING before every upstream stage has had at least one task
  RUNNING (the supervisor's release bar is stricter — full replicas —
  so this is the safe observable core of DAG ordering)
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from ..models.objects import Cluster, Node, Service, Task
from ..models.types import NodeState, TaskState, TERMINAL_STATES, UpdateState
from ..state.events import Event, EventCommit, EventTaskBlock, commit_or


class InvariantViolation(AssertionError):
    pass


class Violations:
    """Shared sink: checkers record, the runner decides pass/fail."""

    def __init__(self, engine):
        self.engine = engine
        self.items: List[str] = []

    def record(self, name: str, msg: str) -> None:
        line = f"INVARIANT {name}: {msg}"
        self.engine.log(line)
        self.items.append(f"t={self.engine.clock.elapsed():.3f} {line}")
        # mark the black box too: the post-mortem dump shows the
        # violation in context (surrounding spans/events), not alone
        from ..obs.flightrec import flightrec
        flightrec.note(line)


def entry_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


class RaftInvariants:
    def __init__(self, violations: Violations):
        self.v = violations
        self.leaders: Dict[int, str] = {}         # term -> leader id
        self.ledger: Dict[int, Tuple[int, str]] = {}  # index -> (term, digest)

    def observe_leader(self, term: int, member_id: str) -> None:
        seen = self.leaders.get(term)
        if seen is None:
            self.leaders[term] = member_id
        elif seen != member_id:
            # an election needs a majority of votes in that term; two
            # distinct winners for one term is a safety violation no
            # matter when each was observed
            self.v.record("single-leader-per-term",
                          f"term {term}: {seen} and {member_id} "
                          "are both leader")

    def observe_apply(self, member_id: str, index: int, term: int,
                      digest: str) -> None:
        seen = self.ledger.get(index)
        if seen is None:
            self.ledger[index] = (term, digest)
        elif seen != (term, digest):
            self.v.record(
                "no-committed-entry-loss",
                f"{member_id} applied ({term},{digest}) at index {index} "
                f"but the cluster committed {seen} there")

    def max_committed(self) -> int:
        return max(self.ledger) if self.ledger else 0


class TaskInvariants:
    """Subscribes to a store's event queue; ``drain()`` must be called
    after every synchronous control-plane step (single-threaded sim, so
    no events are ever in flight between checks)."""

    def __init__(self, violations: Violations, store):
        self.v = violations
        self.store = store
        self.states: Dict[str, int] = {}
        self.desired: Dict[str, int] = {}
        self.node_of: Dict[str, str] = {}
        # node states tracked from the SAME ordered event stream the
        # task observations come from: the assigned-node-live check must
        # compare an assignment against the node state committed BEFORE
        # it, not against the store's current row — drain can run behind
        # the commits (follower catch-up, deferred applies), where a
        # later DOWN would falsely indict an earlier valid assignment
        self.node_states: Dict[str, int] = {}
        self.sub = store.queue.subscribe(
            lambda ev: isinstance(ev, (Event, EventTaskBlock)),
            accepts_blocks=True)
        # adopt the store's committed rows as the baseline: a checker
        # attached to a crash-rebuilt store replays no history, and
        # judging a pre-existing assignment as a fresh transition against
        # a later-arriving node-DOWN event manufactures false positives
        # (single-threaded: nothing commits between subscribe and seed)
        def seed(tx):
            for n in tx.find(Node):
                self.node_states[n.id] = int(n.status.state)
            for t in tx.find(Task):
                self.states[t.id] = int(t.status.state)
                self.desired[t.id] = int(t.desired_state)
                if t.node_id:
                    self.node_of[t.id] = t.node_id
        store.view(seed)

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                return
            if isinstance(ev, EventTaskBlock):
                self._check_block(ev)
                # observe the block's OWN payload (state/node arrays),
                # never the store's current row: drain may run behind a
                # catch-up burst (a rejoined member replaying a long
                # committed suffix), where the store is already ahead of
                # the event being drained — reading "current" there
                # manufactures false FSM regressions
                state = int(ev.state)
                for nid, items in ev.per_node().items():
                    for old, _ver in items:
                        self._observe(old.id, state,
                                      int(old.desired_state), nid)
                continue
            if isinstance(ev.obj, Node):
                if ev.action == "delete":
                    self.node_states.pop(ev.obj.id, None)
                else:
                    self.node_states[ev.obj.id] = \
                        int(ev.obj.status.state)
                continue
            if isinstance(ev.obj, Task) and ev.action != "delete":
                t = ev.obj
                self._observe(t.id, int(t.status.state),
                              int(t.desired_state), t.node_id)

    def _check_block(self, ev: EventTaskBlock) -> None:
        if ev.state > int(TaskState.RUNNING):
            self.v.record(
                "blocks-never-failures",
                f"task block committed state {ev.state} "
                f"(> RUNNING): blocks must only carry assignment states")

    def _observe(self, task_id: str, state: int, des: int,
                 node_id: str) -> None:
        """One observed (state, desired, node) triple for a task, from
        the event payload itself (per-task Event or block column)."""
        prev = self.states.get(task_id)
        if prev is not None:
            if state < prev:
                self.v.record(
                    "fsm-monotonic",
                    f"task {task_id[:8]} moved {TaskState(prev).name} -> "
                    f"{TaskState(state).name}")
            if TaskState(prev) in TERMINAL_STATES and state != prev \
                    and TaskState(state) not in TERMINAL_STATES:
                self.v.record(
                    "terminal-sticky",
                    f"task {task_id[:8]} left terminal "
                    f"{TaskState(prev).name} for {TaskState(state).name}")
        self.states[task_id] = state

        prev_des = self.desired.get(task_id)
        if prev_des is not None and des < prev_des:
            self.v.record(
                "desired-monotonic",
                f"task {task_id[:8]} desired moved "
                f"{TaskState(prev_des).name} -> {TaskState(des).name}")
        self.desired[task_id] = des

        if node_id:
            prev_node = self.node_of.get(task_id)
            if prev_node is not None and prev_node != node_id:
                self.v.record(
                    "no-double-assign",
                    f"task {task_id[:8]} reassigned {prev_node[:8]} -> "
                    f"{node_id[:8]} while live")
            self.node_of[task_id] = node_id

        if state == int(TaskState.ASSIGNED) and prev != state:
            ns = self.node_states.get(node_id) if node_id else None
            if ns is not None:
                # ordered knowledge: the node's last state committed
                # BEFORE this assignment — a DOWN here means the
                # scheduler placed onto a node it knew was dead
                if ns == int(NodeState.DOWN):
                    self.v.record(
                        "assigned-node-live",
                        f"task {task_id[:8]} ASSIGNED to DOWN node "
                        f"{node_id[:8]}")
            else:
                # no ordered knowledge (subscribed mid-stream): at least
                # the node must exist
                node = self.store.raw_get(Node, node_id) \
                    if node_id else None
                if node is None:
                    self.v.record(
                        "assigned-node-live",
                        f"task {task_id[:8]} ASSIGNED to missing node "
                        f"{node_id[:8] if node_id else '<none>'}")


class UpdateInvariants:
    """Rolling-update invariants, tracked from one store's ordered event
    stream (payloads only — the same discipline as TaskInvariants: a
    member draining behind a catch-up burst must never be judged against
    rows newer than the event in hand).

    Completion checks are deferred by ``SETTLE`` virtual seconds: a
    restart racing the updater can legitimately leave one old-version
    task for a beat after COMPLETED lands (the next reconcile's updater
    converges it — reference behavior).  A deferred check is dropped
    when the service's spec version moved on (a newer rollout owns the
    slots now); ``finalize()`` evaluates whatever is still pending at
    scenario end regardless of settle.
    """

    #: virtual seconds a completion check waits before judging
    SETTLE = 15.0

    def __init__(self, violations: Violations, store, tag: str = ""):
        self.v = violations
        self.store = store
        self.tag = tag
        # task id -> immutable spec version index (0 = unversioned)
        self.task_version: Dict[str, int] = {}
        self.task_desired: Dict[str, int] = {}
        self.task_service: Dict[str, str] = {}
        self.task_slot: Dict[str, tuple] = {}
        self.svc_version: Dict[str, int] = {}
        self.svc_state: Dict[str, int] = {}      # UpdateState int; -1 = none
        # sid -> the version a ROLLBACK_STARTED transition rolled back
        # FROM (the restored spec hides it, but expectations are
        # registered against the minted rollout version)
        self._rollback_of: Dict[str, int] = {}
        # sid -> {"version": paused rollout version, "slots": claimed set}
        self.paused: Dict[str, dict] = {}
        #: (t, sid, version, UpdateState int) — every observed transition
        self.history: List[tuple] = []
        #: deferred completion checks: (due_t, sid, version, name)
        self._pending_checks: List[tuple] = []
        self.sub = store.queue.subscribe(
            lambda ev: isinstance(ev, Event)
            and isinstance(ev.obj, (Task, Service)),
            accepts_blocks=True)
        # baseline adoption (see TaskInvariants): a crash-rebuilt store
        # replays no history, so seed tasks and service update states
        # from the committed rows — including a paused rollout's claimed
        # slots, so pause-on-failure-threshold keeps enforcing
        def seed(tx):
            for t in tx.find(Task):
                self.task_version[t.id] = \
                    t.spec_version.index if t.spec_version else 0
                self.task_desired[t.id] = int(t.desired_state)
                self.task_service[t.id] = t.service_id
                self.task_slot[t.id] = (t.slot, t.node_id)
            for s in tx.find(Service):
                version = s.spec_version.index if s.spec_version else 0
                state = int(s.update_status.state) if s.update_status \
                    else -1
                self.svc_version[s.id] = version
                self.svc_state[s.id] = state
                if state in (int(UpdateState.PAUSED),
                             int(UpdateState.ROLLBACK_PAUSED)):
                    # claimed keys carry CREATE-time node ids on the
                    # event path (replicated replacements are minted
                    # with node_id "" before assignment), but committed
                    # rows are already assigned — seed both shapes so a
                    # legitimate restart replacement in an
                    # already-claimed slot never reads as a fresh claim
                    claimed = set()
                    for tid, v in self.task_version.items():
                        if v == version \
                                and self.task_service.get(tid) == s.id:
                            slot_key = self.task_slot[tid]
                            claimed.add(slot_key)
                            claimed.add((slot_key[0], ""))
                    self.paused[s.id] = {"version": version,
                                         "slots": claimed}
        store.view(seed)

    # ---------------------------------------------------------------- drain

    def _now(self) -> float:
        return self.v.engine.clock.elapsed()

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                break
            obj = ev.obj
            if isinstance(obj, Task):
                self._observe_task(ev.action, obj)
            elif isinstance(obj, Service):
                self._observe_service(ev.action, obj)
        self._run_due_checks(self._now())

    def _observe_task(self, action: str, t: Task) -> None:
        if action == "delete":
            self.task_version.pop(t.id, None)
            self.task_desired.pop(t.id, None)
            self.task_service.pop(t.id, None)
            self.task_slot.pop(t.id, None)
            return
        if action == "create":
            version = t.spec_version.index if t.spec_version else 0
            self.task_version[t.id] = version
            self.task_service[t.id] = t.service_id
            self.task_slot[t.id] = (t.slot, t.node_id)
            self._check_pause_progress(t, version)
        self.task_desired[t.id] = int(t.desired_state)

    def _observe_service(self, action: str, s: Service) -> None:
        if action == "delete":
            self.svc_version.pop(s.id, None)
            self.svc_state.pop(s.id, None)
            self.paused.pop(s.id, None)
            return
        version = s.spec_version.index if s.spec_version else 0
        state = int(s.update_status.state) if s.update_status else -1
        prev_state = self.svc_state.get(s.id, -1)
        prev_version = self.svc_version.get(s.id)
        self.svc_version[s.id] = version
        self.svc_state[s.id] = state
        if state == prev_state and version == prev_version:
            return
        self.history.append((self._now(), s.id, version, state))
        if state == int(UpdateState.ROLLBACK_STARTED) \
                and prev_version is not None and prev_version != version:
            self._rollback_of[s.id] = prev_version
        rb = self._rollback_of.get(s.id)
        if rb is not None and state in (int(UpdateState.ROLLBACK_STARTED),
                                        int(UpdateState.ROLLBACK_PAUSED),
                                        int(UpdateState.ROLLBACK_COMPLETED)):
            # mirror rollback states onto the rolled-back version so
            # expect_update(minted_version, ROLLBACK_COMPLETED) matches
            self.history.append((self._now(), s.id, rb, state))
        elif rb is not None and (state == -1 or version > rb):
            self._rollback_of.pop(s.id, None)
        if state != prev_state:
            if state in (int(UpdateState.COMPLETED),
                         int(UpdateState.ROLLBACK_COMPLETED)):
                name = ("rollback-restores-old-spec-everywhere"
                        if state == int(UpdateState.ROLLBACK_COMPLETED)
                        else "no-mixed-version-after-completion")
                self._pending_checks.append(
                    (self._now() + self.SETTLE, s.id, version, name))
            if state in (int(UpdateState.PAUSED),
                         int(UpdateState.ROLLBACK_PAUSED)):
                self.paused[s.id] = {
                    "version": version,
                    "slots": {self.task_slot[tid]
                              for tid, v in self.task_version.items()
                              if v == version
                              and self.task_service.get(tid) == s.id
                              and tid in self.task_slot}}
            else:
                self.paused.pop(s.id, None)

    # -------------------------------------------------------------- checks

    def _check_pause_progress(self, t: Task, version: int) -> None:
        """A paused update must not claim NEW slots for the paused
        version.  Restart replacements in already-claimed slots are
        legitimate (pausing stops the rollout, not restart management)."""
        p = self.paused.get(t.service_id)
        if p is None or version != p["version"]:
            return
        key = (t.slot, t.node_id)
        if key in p["slots"]:
            return
        p["slots"].add(key)   # record once per slot
        self.v.record(
            "pause-on-failure-threshold",
            f"{self.tag}: service {t.service_id} claimed new slot "
            f"{key} for version {version} while the update is paused")

    def _run_due_checks(self, ts: float) -> None:
        still = []
        for due, sid, version, name in self._pending_checks:
            if ts < due:
                still.append((due, sid, version, name))
                continue
            self._judge_completion(sid, version, name)
        self._pending_checks = still

    def _judge_completion(self, sid: str, version: int, name: str) -> None:
        if self.svc_version.get(sid) != version:
            return   # a newer rollout owns the slots now
        mixed = [
            tid for tid, v in self.task_version.items()
            if self.task_service.get(tid) == sid and v != version
            and self.task_desired.get(tid, 0) <= int(TaskState.RUNNING)]
        if mixed:
            self.v.record(
                name,
                f"{self.tag}: service {sid} completed at version "
                f"{version} but {len(mixed)} live task(s) carry other "
                f"versions (e.g. {sorted(mixed)[:3]})")

    def finalize(self) -> None:
        """Scenario end: judge every still-pending completion check —
        the end state must be clean regardless of settle windows."""
        self.drain()
        for _due, sid, version, name in self._pending_checks:
            self._judge_completion(sid, version, name)
        self._pending_checks = []


class PreemptionInvariants:
    """Priority & preemption invariants, tracked from one store's
    ordered event stream (payload discipline like TaskInvariants):

    * no-preempt-equal-or-higher — every preemption marker
      (``swarm.preempted.*`` annotations stamped by the scheduler's
      atomic preemption tx) must name a victim priority STRICTLY below
      the preemptor's; equal-or-higher anywhere is a safety violation.
    * no-priority-inversion — a positive-priority task that stays
      PENDING past ``inversion_bound`` virtual seconds while some node
      it fits (resource-wise, counting the reservations of its
      strictly-lower-priority running tasks as reclaimable) holds
      lower-priority work is an inversion the preemption pass should
      have resolved.
    * preemption-thrash-bound — one slot preempted more than
      ``thrash_bound`` times inside ``thrash_window`` virtual seconds
      is thrash the anti-thrash cooldown exists to prevent.
    * preempted-tasks-requeue (``finalize``) — every preempted victim's
      slot must hold a NEWER runnable (or completed) task by scenario
      end, unless the service shrank below the slot or was deleted:
      preemption evicts work, it never loses it.
    """

    def __init__(self, violations: Violations, store, tag: str = "",
                 inversion_bound: float = 25.0, thrash_bound: int = 3,
                 thrash_window: float = 60.0):
        self.v = violations
        self.store = store
        self.tag = tag
        self.inversion_bound = inversion_bound
        self.thrash_bound = thrash_bound
        self.thrash_window = thrash_window
        #: pending positive-priority unassigned tasks -> first-seen t
        self.pending_since: Dict[str, float] = {}
        self._judged: set = set()
        self._seen_markers: set = set()
        self._thrash_flagged: set = set()
        self._slot_stamps: Dict[tuple, List[float]] = {}
        #: (t, service_id, slot, node_id, victim_id) per observed marker
        self.preempted: List[tuple] = []
        self.seen_preemptions = 0
        from ..scheduler.preempt import task_priority
        self._priority = task_priority
        self.sub = store.queue.subscribe(
            lambda ev: isinstance(ev, Event)
            and isinstance(ev.obj, Task), accepts_blocks=True)

        # baseline adoption (TaskInvariants discipline): a crash-rebuilt
        # store replays no history — seed pending-age tracking from the
        # committed rows so a long-pending inversion survives the crash
        def seed(tx):
            ts = self._now()
            for t in tx.find(Task):
                if (not t.node_id
                        and t.status.state == int(TaskState.PENDING)
                        and t.desired_state <= int(TaskState.COMPLETE)
                        and self._priority(t) > 0):
                    self.pending_since[t.id] = ts
                if "swarm.preempted.at" in t.annotations.labels:
                    self._seen_markers.add(t.id)
        store.view(seed)

    def _now(self) -> float:
        return self.v.engine.clock.elapsed()

    # ---------------------------------------------------------------- drain

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                break
            t = ev.obj
            if ev.action == "delete":
                self.pending_since.pop(t.id, None)
                continue
            if (not t.node_id
                    and t.status.state == int(TaskState.PENDING)
                    and t.desired_state <= int(TaskState.COMPLETE)
                    and self._priority(t) > 0):
                self.pending_since.setdefault(t.id, self._now())
            else:
                self.pending_since.pop(t.id, None)
            labels = t.annotations.labels
            if "swarm.preempted.at" in labels \
                    and t.id not in self._seen_markers:
                self._seen_markers.add(t.id)
                self._observe_preemption(t, labels)
        ts = self._now()
        for tid, since in list(self.pending_since.items()):
            if ts - since > self.inversion_bound:
                self._judge_inversion(tid, ts)

    def _observe_preemption(self, t: Task, labels: Dict[str, str]) -> None:
        self.seen_preemptions += 1
        try:
            victim_prio = int(labels.get("swarm.preempted.prio", "0"))
            by_prio = int(labels.get("swarm.preempted.by.prio", "0"))
        except ValueError:
            victim_prio, by_prio = 0, 0
        if victim_prio >= by_prio:
            self.v.record(
                "no-preempt-equal-or-higher",
                f"{self.tag}: task {t.id[:8]} (priority {victim_prio}) "
                f"preempted by priority {by_prio} work — victims must "
                "be strictly lower")
        ts = self._now()
        key = (t.service_id, t.slot, t.node_id if not t.slot else "")
        stamps = [s for s in self._slot_stamps.get(key, [])
                  if ts - s < self.thrash_window] + [ts]
        self._slot_stamps[key] = stamps
        if len(stamps) > self.thrash_bound \
                and key not in self._thrash_flagged:
            self._thrash_flagged.add(key)
            self.v.record(
                "preemption-thrash-bound",
                f"{self.tag}: slot {key} preempted {len(stamps)} times "
                f"inside {self.thrash_window:.0f}s (bound "
                f"{self.thrash_bound}) — anti-thrash cooldown broken")
        self.preempted.append((ts, t.service_id, t.slot, t.node_id,
                               t.id))

    # --------------------------------------------------------------- checks

    def _judge_inversion(self, tid: str, ts: float) -> None:
        """Judge one overdue pending task.  A clean verdict RE-ARMS the
        stamp (the task is judged again after another bound) — an
        inversion that only develops later must still be caught; a
        recorded violation stops tracking (one report per task)."""
        if tid in self._judged:
            self.pending_since.pop(tid, None)
            return
        task = self.store.raw_get(Task, tid)
        if task is None or task.node_id \
                or task.status.state != int(TaskState.PENDING):
            self.pending_since.pop(tid, None)
            return
        p = self._priority(task)
        res = task.spec.resources.reservations if task.spec.resources \
            else None
        if res is None or (not res.nano_cpus and not res.memory_bytes) \
                or res.generic:
            # non-resource infeasibility: not preemption's job, and it
            # cannot become one — stop tracking this task
            self.pending_since.pop(tid, None)
            return
        cpu_d, mem_d = int(res.nano_cpus), int(res.memory_bytes)

        def scan(tx):
            from ..scheduler.nodeinfo import task_reservations
            by_node: Dict[str, list] = {}
            for t in tx.find(Task):
                if t.node_id and t.desired_state <= int(TaskState.COMPLETE) \
                        and t.status.state <= int(TaskState.RUNNING):
                    by_node.setdefault(t.node_id, []).append(t)
            for n in tx.find(Node):
                if n.status.state != int(NodeState.READY) \
                        or n.spec.availability != 0 \
                        or not n.description or not n.description.resources:
                    continue
                free_cpu = int(n.description.resources.nano_cpus)
                free_mem = int(n.description.resources.memory_bytes)
                reclaim_cpu = reclaim_mem = 0
                lower = False
                for t in by_node.get(n.id, []):
                    r = task_reservations(t)
                    free_cpu -= int(r.nano_cpus)
                    free_mem -= int(r.memory_bytes)
                    if self._priority(t) < p \
                            and t.status.state == int(TaskState.RUNNING):
                        lower = True
                        reclaim_cpu += int(r.nano_cpus)
                        reclaim_mem += int(r.memory_bytes)
                if lower and free_cpu + reclaim_cpu >= cpu_d \
                        and free_mem + reclaim_mem >= mem_d:
                    return n.id
            return None

        node = self.store.view(scan)
        if node is not None:
            self._judged.add(tid)
            self.pending_since.pop(tid, None)
            self.v.record(
                "no-priority-inversion",
                f"{self.tag}: task {tid[:8]} (priority {p}) pending > "
                f"{self.inversion_bound:.0f}s while lower-priority work "
                f"on node {node} covers its demand — preemption should "
                "have resolved this")
        else:
            # clean right now: re-arm — an inversion may develop later
            self.pending_since[tid] = ts

    def finalize(self) -> None:
        """Scenario end: every preempted slot must have been requeued —
        a newer task occupies the (service, slot), or the service
        legitimately shrank/vanished."""
        self.drain()

        def judge(tx):
            missing = []
            for ts, sid, slot, node_id, victim_id in self.preempted:
                svc = tx.get(Service, sid)
                if svc is None:
                    continue
                if svc.spec.replicated is not None \
                        and svc.spec.replicated.replicas < slot:
                    continue    # scaled below the slot: no requeue owed
                again = [t for t in tx.find(Task)
                         if t.service_id == sid and t.slot == slot
                         and t.id != victim_id
                         and (t.desired_state <= int(TaskState.COMPLETE)
                              or t.status.state
                              == int(TaskState.COMPLETE))]
                if not again:
                    missing.append((sid, slot, victim_id))
            return missing

        for sid, slot, victim_id in self.store.view(judge):
            self.v.record(
                "preempted-tasks-requeue",
                f"{self.tag}: victim {victim_id[:8]} of service {sid} "
                f"slot {slot} was never requeued — preemption lost "
                "work")


class QosInvariants:
    """Autoscaler + multi-tenant QoS invariants (ISSUE 12), tracked from
    one store's ordered event stream (payload discipline like
    TaskInvariants):

    * quota-never-exceeded — committed per-tenant usage (cpu/memory
      reservations + task count of assigned, live tasks) must stay <=
      the ClusterSpec quota at every drain.  Usage is re-derived from
      event payloads, independently of the scheduler's ledger.
    * autoscale-within-bounds-and-rate — every committed replica change
      on an autoscaled service must land inside [min, max], move at
      most one configured step, and carry decision stamps
      (``Service.autoscale_status.last_decision_at`` — the REPLICATED
      stamp, so the check holds across leader failover) no closer than
      the stabilization window.
    * no-cross-band-p99-violation (``check_band_p99``) — a registered
      burst window must not degrade higher bands' pending->assigned
      p99 beyond a bound derived from the scheduler's own cadence
      (control-step interval + commit-debounce latency) and the band's
      own out-of-window behavior — never a per-scenario constant.
      Tasks still pending at finalize count at their open-ended age, so
      outright starvation cannot hide from a percentile.
    * autoscale-converges — judged by the control plane's registered
      expectations against ``replica_history`` (merged across members
      and crash-rebuilt checkers, like the update-state history).
    """

    #: slack on the rate check: equal stamps one float ulp apart must
    #: not fire
    RATE_EPS = 1e-6

    def __init__(self, violations: Violations, store, tag: str = "",
                 cadence: float = 1.5):
        self.v = violations
        self.store = store
        self.tag = tag
        #: scheduler cadence (control interval + debounce max latency):
        #: the latency floor the p99 bound derives from
        self.cadence = cadence
        self.quotas: Dict[str, object] = {}
        #: task id -> (tenant, cpu, mem) currently counted toward usage
        self._counted: Dict[str, tuple] = {}
        self.usage: Dict[str, List[int]] = {}
        self._quota_flagged: set = set()
        #: service id -> (replicas, autoscale cfg, decision stamp)
        self._svc_replicas: Dict[str, int] = {}
        self._svc_stamp: Dict[str, float] = {}
        self._bounds_flagged: set = set()
        #: (t, service id, replicas) — every committed replica change
        #: on an autoscaled service
        self.replica_history: List[tuple] = []
        #: task id -> (priority, first-PENDING stamp) still waiting
        self.pending_open: Dict[str, tuple] = {}
        #: (task id, priority, assign t, pending->assigned latency) —
        #: the id lets the control plane dedupe samples across member
        #: checkers (every member observes the same committed stream)
        self.band_samples: List[tuple] = []
        self.sub = store.queue.subscribe(
            lambda ev: isinstance(ev, EventTaskBlock)
            or (isinstance(ev, Event)
                and isinstance(ev.obj, (Task, Service, Cluster))),
            accepts_blocks=True)

        from ..scheduler.nodeinfo import task_reservations
        from ..scheduler.preempt import task_priority
        from ..scheduler.quota import task_tenant
        self._reservations = task_reservations
        self._priority = task_priority
        self._tenant = task_tenant

        # baseline adoption (TaskInvariants discipline): a crash-rebuilt
        # store replays no history — seed quotas, usage, service state
        # and open pending stamps from the committed rows
        def seed(tx):
            ts = self._now()
            for c in tx.find(Cluster):
                if c.spec.annotations.name == "default":
                    self.quotas = dict(c.spec.tenants)
            for s in tx.find(Service):
                if s.spec.autoscale is not None \
                        and s.spec.replicated is not None:
                    self._svc_replicas[s.id] = s.spec.replicated.replicas
                    if s.autoscale_status is not None:
                        self._svc_stamp[s.id] = \
                            s.autoscale_status.last_decision_at
            for t in tx.find(Task):
                self._observe_task_row(t, ts)
        store.view(seed)

    def _now(self) -> float:
        return self.v.engine.clock.elapsed()

    # ---------------------------------------------------------------- drain

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                break
            ts = self._now()
            if isinstance(ev, EventTaskBlock):
                # block payloads: per-task (old row, node) pairs plus
                # the committed state column — the scheduler's columnar
                # assignment commits arrive exactly this way
                state = int(ev.state)
                for nid, items in ev.per_node().items():
                    for old, _ver in items:
                        self._observe_task_payload(
                            old, state, nid, int(old.desired_state), ts)
                continue
            obj = ev.obj
            if isinstance(obj, Cluster):
                # the "default" cluster owns the quota table (the same
                # row the scheduler reads) — other Cluster objects must
                # not wipe it
                if ev.action != "delete" \
                        and obj.spec.annotations.name == "default":
                    self.quotas = dict(obj.spec.tenants)
                continue
            if isinstance(obj, Service):
                self._observe_service(ev.action, obj, ts)
                continue
            if ev.action == "delete":
                self._uncount(obj.id)
                self.pending_open.pop(obj.id, None)
                continue
            self._observe_task_row(obj, ts)
        self._check_quota()

    # -------------------------------------------------------------- tenants

    def _observe_task_row(self, t: Task, ts: float) -> None:
        self._observe_task_payload(t, int(t.status.state), t.node_id,
                                   int(t.desired_state), ts)

    def _observe_task_payload(self, t: Task, state: int, node_id: str,
                              desired: int, ts: float) -> None:
        # usage: counted while assigned and live
        live = (bool(node_id)
                and int(TaskState.ASSIGNED) <= state
                <= int(TaskState.RUNNING)
                and desired <= int(TaskState.COMPLETE))
        if live and t.id not in self._counted:
            tenant = self._tenant(t)
            if tenant in self.quotas:
                res = self._reservations(t)
                entry = (tenant, int(res.nano_cpus),
                         int(res.memory_bytes))
                self._counted[t.id] = entry
                row = self.usage.setdefault(tenant, [0, 0, 0])
                row[0] += entry[1]
                row[1] += entry[2]
                row[2] += 1
        elif not live and t.id in self._counted:
            self._uncount(t.id)
        # pending->assigned band latency.  Terminal-past-RUNNING is
        # checked FIRST: a task shut down while still PENDING (scale
        # down, reaper) was never assigned and must not mint a sample.
        if state > int(TaskState.RUNNING):
            self.pending_open.pop(t.id, None)
        elif (state == int(TaskState.PENDING) and not node_id
                and desired <= int(TaskState.COMPLETE)):
            self.pending_open.setdefault(t.id, (self._priority(t), ts))
        elif state >= int(TaskState.ASSIGNED):
            open_ = self.pending_open.pop(t.id, None)
            if open_ is not None:
                prio, since = open_
                self.band_samples.append((t.id, prio, ts, ts - since))

    def _uncount(self, task_id: str) -> None:
        entry = self._counted.pop(task_id, None)
        if entry is None:
            return
        tenant, cpu, mem = entry
        row = self.usage.get(tenant)
        if row is not None:
            row[0] -= cpu
            row[1] -= mem
            row[2] -= 1

    def _check_quota(self) -> None:
        for tenant, q in self.quotas.items():
            if tenant in self._quota_flagged:
                continue
            row = self.usage.get(tenant)
            if row is None:
                continue
            over = []
            for have, limit, unit in ((row[0], q.nano_cpus, "nano_cpus"),
                                      (row[1], q.memory_bytes,
                                       "memory_bytes"),
                                      (row[2], q.max_tasks, "tasks")):
                if limit > 0 and have > limit:
                    over.append(f"{unit} {have} > {limit}")
            if over:
                self._quota_flagged.add(tenant)
                self.v.record(
                    "quota-never-exceeded",
                    f"{self.tag}: tenant {tenant} committed usage "
                    f"exceeds its quota ({'; '.join(over)}) — admission "
                    "clamping is broken")

    # ------------------------------------------------------------ autoscale

    def _observe_service(self, action: str, s: Service,
                         ts: float) -> None:
        if action == "delete":
            self._svc_replicas.pop(s.id, None)
            self._svc_stamp.pop(s.id, None)
            return
        cfg = s.spec.autoscale
        if cfg is None or s.spec.replicated is None:
            self._svc_replicas.pop(s.id, None)
            return
        new = s.spec.replicated.replicas
        prev = self._svc_replicas.get(s.id)
        stamp = (s.autoscale_status.last_decision_at
                 if s.autoscale_status is not None else 0.0)
        prev_stamp = self._svc_stamp.get(s.id)
        self._svc_replicas[s.id] = new
        if stamp:
            self._svc_stamp[s.id] = stamp
        if prev is None or new == prev:
            return
        self.replica_history.append((ts, s.id, new))
        problems = []
        if not (cfg.min_replicas <= new <= cfg.max_replicas):
            problems.append(
                f"replicas {new} outside "
                f"[{cfg.min_replicas}, {cfg.max_replicas}]")
        step = cfg.scale_up_step if new > prev else cfg.scale_down_step
        if abs(new - prev) > max(step, 1):
            problems.append(
                f"step {prev} -> {new} exceeds the configured "
                f"{'up' if new > prev else 'down'} step {step}")
        if (prev_stamp and stamp
                and stamp - prev_stamp
                < cfg.stabilization_window - self.RATE_EPS):
            problems.append(
                f"decision stamps {prev_stamp:.3f} -> {stamp:.3f} are "
                f"closer than the {cfg.stabilization_window:.1f}s "
                "stabilization window")
        if problems and s.id not in self._bounds_flagged:
            self._bounds_flagged.add(s.id)
            self.v.record(
                "autoscale-within-bounds-and-rate",
                f"{self.tag}: service {s.id}: {'; '.join(problems)}")

    # -------------------------------------------------------------- finalize

    @staticmethod
    def _p99(samples: List[float]) -> float:
        if not samples:
            return 0.0
        ordered = sorted(samples)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * len(ordered)))]

    def band_p99_bound(self, baseline: List[float]) -> float:
        """The derived bound: the scheduler's own cadence (a handful of
        control steps + commit debounce) as the floor, or 3x the band's
        out-of-window p99 when that behavior is worse — never a
        per-scenario constant."""
        return max(4.0 * self.cadence, 3.0 * self._p99(baseline))

    def check_band_p99(self, min_priority: int, t0: float, t1: float,
                       violations: Violations,
                       samples: Optional[List[tuple]] = None,
                       open_pending: Optional[List[tuple]] = None
                       ) -> None:
        """Judge one registered burst window: higher bands' (priority >=
        ``min_priority``) pending->assigned p99 inside [t0, t1] must stay
        under the derived bound.  ``samples``/``open_pending`` default to
        this checker's own view (the control plane passes merged,
        deduped collections)."""
        samples = samples if samples is not None else self.band_samples
        if open_pending is None:
            open_pending = list(self.pending_open.values())
        ts = self._now()
        band = [(at, lat) for _tid, prio, at, lat in samples
                if prio >= min_priority]
        in_window = [lat for at, lat in band if t0 <= at <= t1]
        # a task of the band still unassigned counts at its open-ended
        # age — starvation must not escape the percentile
        for prio, since in open_pending:
            if prio >= min_priority and since <= t1:
                in_window.append(ts - since)
        if not in_window:
            violations.record(
                "no-cross-band-p99-violation",
                f"band >= {min_priority} produced no pending->assigned "
                f"samples in [{t0:.0f}, {t1:.0f}] — the burst window "
                "never exercised the protected band")
            return
        baseline = [lat for at, lat in band if at < t0 or at > t1]
        bound = self.band_p99_bound(baseline)
        p99 = self._p99(in_window)
        if p99 > bound:
            violations.record(
                "no-cross-band-p99-violation",
                f"band >= {min_priority} pending->assigned p99 "
                f"{p99:.2f}s inside the burst window exceeds the "
                f"derived bound {bound:.2f}s (cadence {self.cadence}s, "
                f"baseline p99 {self._p99(baseline):.2f}s over "
                f"{len(baseline)} samples) — the burst leaked into the "
                "protected band")


class GangInvariants:
    """Gang-scheduling atomicity (ISSUE 16), tracked from one store's
    ordered event stream with commit boundaries:

    * gang-atomicity — no committed transaction may assign a strict
      subset of a gang unit: at every ``EventCommit``, a unit that had
      members assigned in the batch while OTHER members of the unit
      remain pending is *suspected*.  A suspicion resolves silently if
      those members stop being pending (placed by the immediately
      following tick, or shut down) within ``GRACE`` seconds — that is
      the legal race where the orchestrator materializes a replacement
      between the scheduler's snapshot and its commit.  A suspicion
      that outlives the grace window is a real partial placement and
      fails the run.

    Pending membership is derived from event payloads only (never
    current store rows), TaskInvariants discipline; a crash-rebuilt
    checker seeds from the committed rows.
    """

    #: seconds a strict-subset suspicion may stay open before it is a
    #: violation — a few scheduler cadences, so the one-tick
    #: snapshot/commit race always resolves and a deferred
    #: "partially placed" remainder never does
    GRACE = 10.0

    def __init__(self, violations: Violations, store, tag: str = ""):
        self.v = violations
        self.store = store
        self.tag = tag
        from ..scheduler.gang import gang_unit, is_gang
        self._gang_unit = gang_unit
        self._is_gang = is_gang
        #: pending gang members: task id -> unit key
        self._pending: Dict[str, str] = {}
        #: unit -> task ids assigned in the current commit batch
        self._batch: Dict[str, set] = {}
        #: unit -> (suspected-at, frozenset of left-behind task ids)
        self._suspect: Dict[str, tuple] = {}
        self._flagged: set = set()
        self.stats = {"commits_judged": 0, "suspicions": 0,
                      "resolved": 0}
        self.sub = store.queue.subscribe(
            commit_or(lambda ev: isinstance(ev, EventTaskBlock)
                      or (isinstance(ev, Event)
                          and isinstance(ev.obj, Task))),
            accepts_blocks=True)
        # baseline adoption: seed the pending set from committed rows
        # (assignments already committed are history, not a batch)
        def seed(tx):
            for t in tx.find(Task):
                self._observe(t, int(t.status.state), t.node_id,
                              int(t.desired_state))
        store.view(seed)
        self._batch.clear()

    def _now(self) -> float:
        return self.v.engine.clock.elapsed()

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                break
            if isinstance(ev, EventCommit):
                self._judge_batch()
                continue
            if isinstance(ev, EventTaskBlock):
                state = int(ev.state)
                for nid, items in ev.per_node().items():
                    for old, _ver in items:
                        self._observe(old, state, nid,
                                      int(old.desired_state))
                continue
            obj = ev.obj
            if ev.action == "delete":
                self._pending.pop(obj.id, None)
                continue
            self._observe(obj, int(obj.status.state), obj.node_id,
                          int(obj.desired_state))
        self._age_suspicions()

    def _observe(self, t: Task, state: int, node_id: str,
                 desired: int) -> None:
        if not self._is_gang(t):
            return
        unit = self._gang_unit(t)
        if (not node_id and state == int(TaskState.PENDING)
                and desired <= int(TaskState.COMPLETE)):
            self._pending[t.id] = unit
            return
        was_pending = self._pending.pop(t.id, None) is not None
        if (was_pending and node_id
                and state >= int(TaskState.ASSIGNED)
                and desired <= int(TaskState.COMPLETE)):
            self._batch.setdefault(unit, set()).add(t.id)
        # anything else — shut down, failed, orphaned — just stops
        # being pending; only pending->assigned joins the batch

    def _judge_batch(self) -> None:
        batch, self._batch = self._batch, {}
        if not batch:
            return
        self.stats["commits_judged"] += 1
        for unit, assigned in batch.items():
            if unit in self._flagged or unit in self._suspect:
                continue
            left = frozenset(tid for tid, u in self._pending.items()
                             if u == unit)
            if left:
                self.stats["suspicions"] += 1
                self._suspect[unit] = (self._now(), left, len(assigned))

    def _age_suspicions(self) -> None:
        if not self._suspect:
            return
        ts = self._now()
        for unit in list(self._suspect):
            since, left, n_assigned = self._suspect[unit]
            still = [tid for tid in left if self._pending.get(tid) == unit]
            if not still:
                self.stats["resolved"] += 1
                del self._suspect[unit]
                continue
            if ts - since > self.GRACE and unit not in self._flagged:
                self._flagged.add(unit)
                del self._suspect[unit]
                self.v.record(
                    "gang-atomicity",
                    f'{self.tag}: a commit at t={since:.1f} assigned '
                    f'{n_assigned} member(s) of gang "{unit}" while '
                    f'{len(still)} member(s) stayed pending '
                    f'{ts - since:.1f}s past the commit — a strict '
                    "subset of a gang was committed")


class PipelineInvariants:
    """Pipeline DAG ordering (ISSUE 16), tracked from one store's
    ordered event stream:

    * pipeline-order — a task of a service that names upstream
      dependencies (``ServiceSpec.depends_on``) must never be observed
      RUNNING before every upstream service has had at least one task
      reach RUNNING (COMPLETE counts: a finished job ran).  The
      supervisor's release bar is stricter (full replicas / total
      completions), so this is the safe observable core — it cannot
      false-positive on upstream churn after release, yet fires the
      moment the gate is bypassed.

    Ever-RUNNING is sticky per service; a crash-rebuilt checker seeds
    it leniently (status >= RUNNING) from committed rows so failover
    cannot mint false positives.
    """

    def __init__(self, violations: Violations, store, tag: str = ""):
        self.v = violations
        self.store = store
        self.tag = tag
        #: service id -> upstream names; service name -> id
        self._depends: Dict[str, List[str]] = {}
        self._by_name: Dict[str, str] = {}
        #: service ids with >= 1 task ever observed RUNNING
        self._ever_ran: set = set()
        self._flagged: set = set()
        self.sub = store.queue.subscribe(
            lambda ev: isinstance(ev, EventTaskBlock)
            or (isinstance(ev, Event)
                and isinstance(ev.obj, (Task, Service))),
            accepts_blocks=True)

        def seed(tx):
            for s in tx.find(Service):
                self._observe_service("update", s)
            for t in tx.find(Task):
                if t.status.state >= int(TaskState.RUNNING):
                    self._ever_ran.add(t.service_id)
        store.view(seed)

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                break
            if isinstance(ev, EventTaskBlock):
                # assignment-band block commits never carry RUNNING by
                # contract; guarded anyway so a future block shape
                # cannot silently skip the ordering check
                if int(ev.state) == int(TaskState.RUNNING):
                    for _nid, items in ev.per_node().items():
                        for old, _ver in items:
                            self._observe_running(old)
                continue
            obj = ev.obj
            if isinstance(obj, Service):
                self._observe_service(ev.action, obj)
                continue
            if ev.action == "delete":
                continue
            state = int(obj.status.state)
            if state == int(TaskState.RUNNING) \
                    or state == int(TaskState.COMPLETE):
                self._observe_running(obj)

    def _observe_service(self, action: str, s: Service) -> None:
        name = s.spec.annotations.name
        if action == "delete":
            self._depends.pop(s.id, None)
            if self._by_name.get(name) == s.id:
                del self._by_name[name]
            return
        self._by_name[name] = s.id
        deps = list(s.spec.depends_on or ())
        if deps:
            self._depends[s.id] = deps
        else:
            self._depends.pop(s.id, None)

    def _observe_running(self, t: Task) -> None:
        sid = t.service_id
        deps = self._depends.get(sid)
        if deps and sid not in self._flagged:
            not_ready = []
            for dep in deps:
                up = self._by_name.get(dep)
                if up is None or up not in self._ever_ran:
                    not_ready.append(dep)
            if not_ready:
                self._flagged.add(sid)
                self.v.record(
                    "pipeline-order",
                    f"{self.tag}: task {t.id} of pipeline stage "
                    f"{sid} reached RUNNING before upstream stage(s) "
                    f"{', '.join(repr(d) for d in not_ready)} ever ran "
                    "— the DAG gate was bypassed")
        # sticky AFTER the check (self-edges are rejected by the
        # control API, so ordering here cannot self-satisfy)
        self._ever_ran.add(sid)


class ReadInvariants:
    """Follower-served read-plane invariants, judged at read-serve time
    (the proposers' ``read_barrier`` calls in — no event stream needed,
    a read is a synchronous act):

    * follower-reads-never-uncommitted — a linearizable view served by
      ANY member must include every entry committed cluster-wide at the
      moment the read was requested (and can never run ahead of the
      sealed ledger: members only apply committed entries).  Serving a
      view without waiting out the read barrier is exactly the bug this
      catches.
    * lease-read-safe-under-skew — a leader-lease read (quorum-free fast
      path) is only safe while the lease's election-timing argument
      holds: never under an active clock-skew fault (skewed tick rates
      void the "no one can have been elected yet" claim), and never from
      a member whose state trails the cluster's committed frontier (an
      expired-lease ex-leader serving is a stale read).
    """

    def __init__(self, violations: Violations, managers):
        self.v = violations
        self.managers = managers
        self.stats = {"reads": 0, "lease_reads": 0, "stale_serves": 0}

    def committed_version(self) -> int:
        """The cluster's sealed store-version frontier: member stores
        only apply committed entries, so the max version any member
        reached IS the committed watermark a linearizable read must
        cover."""
        best = 0
        for m in self.managers:
            if m.store is not None:
                v = m.store.version
                if v > best:
                    best = v
        return best

    def begin_read(self, member) -> dict:
        return {"required": self.committed_version()}

    def _stale(self) -> None:
        from ..utils.metrics import registry as _metrics
        self.stats["stale_serves"] += 1
        # the counter obs/health.py's stale_read_risk check fails on
        _metrics.counter("swarm_stale_reads")

    def served(self, member, token: dict, lease: bool,
               skew_active: bool) -> None:
        self.stats["reads"] += 1
        v = member.store.version if member.store is not None else 0
        if v < token["required"]:
            self._stale()
            self.v.record(
                "follower-reads-never-uncommitted",
                f"{member.id} served a linearizable view at store "
                f"version {v}, missing committed entries up to "
                f"{token['required']} — the read barrier was skipped "
                "or broken")
        if lease:
            self.stats["lease_reads"] += 1
            if skew_active:
                self.v.record(
                    "lease-read-safe-under-skew",
                    f"{member.id} served a lease read while a "
                    "clock-skew fault is active — skew voids the "
                    "lease's election-timing argument; it must fall "
                    "back to a read-index quorum round")
            if v < token["required"]:
                # judged against the REQUEST-time frontier (entries
                # committing while the response is in flight are not a
                # linearizability violation): an expired-lease ex-leader
                # honoring its lease lands here
                self.v.record(
                    "lease-read-safe-under-skew",
                    f"{member.id} served a lease read at version {v} "
                    "behind the committed frontier "
                    f"{token['required']} at request time — an expired "
                    "or stale lease was honored")


class OverloadInvariants:
    """Overload-protection-plane invariants (ISSUE 20), judged at
    scenario end against two ledgers:

    * overload-sheds-are-counted-and-recovered — degraded is never
      silently lossy.  Two halves: (1) every shed a CLIENT observed
      (an ``ErrOverloaded`` on a registration or a status batch) must
      be covered by the dispatcher-side shed ledger
      (``stats["sheds"]`` accumulated across attach epochs and read
      planes) — a shed the server didn't count is invisible to
      operators; (2) every task whose status update was shed must
      reach AT LEAST the shed state — or some terminal state, or be
      deleted — in the authoritative store once load subsides: the
      client's level-triggered re-derive plus the jittered backoff
      must have recovered it.
    * heartbeat-liveness-under-stretch — adaptive heartbeat-period
      stretching may slow the cadence, but a node must NEVER be
      expired inside the window the dispatcher PROMISED it (the
      dispatcher counts such expiries as ``premature_expirations``;
      only reachable with the ``stretch_extends_deadline`` seam off).
    """

    def __init__(self, violations: Violations, cp):
        self.v = violations
        self.cp = cp
        #: sheds as the CLIENTS saw them: one per shed registration,
        #: len(batch) per shed status batch
        self.client_sheds = 0
        #: task id -> highest shed state the client tried to report
        self.shed_tasks: Dict[str, int] = {}

    def note_client_shed(self, node_id: str, updates) -> None:
        """Called by the agent the instant it catches ErrOverloaded.
        ``updates`` is the shed (task_id, TaskStatus) batch, or None
        for a shed registration."""
        if updates is None:
            self.client_sheds += 1
            return
        self.client_sheds += len(updates)
        for tid, status in updates:
            st = int(status.state)
            if st > self.shed_tasks.get(tid, 0):
                self.shed_tasks[tid] = st

    def finalize(self) -> None:
        counted = self.cp.dispatcher_stats.get("sheds", 0)
        if self.client_sheds > counted:
            self.v.record(
                "overload-sheds-are-counted-and-recovered",
                f"clients observed {self.client_sheds} admission sheds "
                f"but the dispatcher ledger counted only {counted} — "
                "degradation went silently unaccounted")
        store = self.cp.store
        if store is not None and self.shed_tasks:
            rows = {t.id: t for t in store.view(
                lambda tx: tx.find(Task))}
            lost = []
            for tid, shed_state in sorted(self.shed_tasks.items()):
                t = rows.get(tid)
                if t is None:
                    continue   # reaped/removed: nothing to recover
                got = int(t.status.state)
                # recovered: the store caught up to (or past) what the
                # client tried to report, or the task reached SOME
                # terminal outcome that supersedes the shed report
                if got >= shed_state or got > int(TaskState.RUNNING):
                    continue
                lost.append((tid, shed_state, got))
            if lost:
                tid, shed_state, got = lost[0]
                self.v.record(
                    "overload-sheds-are-counted-and-recovered",
                    f"{len(lost)} shed status update(s) never recovered "
                    f"after heal+grace — e.g. task {tid[:12]} was shed "
                    f"reporting {TaskState(shed_state).name} but the "
                    f"store still shows {TaskState(got).name}")
        premature = self.cp.dispatcher_stats.get(
            "premature_expirations", 0)
        if premature:
            self.v.record(
                "heartbeat-liveness-under-stretch",
                f"{premature} session(s) were expired INSIDE their "
                "promised heartbeat window — the stretched period was "
                "promised to the agent but not honored by the expiry "
                "deadline")


class WatchContinuity:
    """Reference ledger + judgment for ``watch-resume-no-gap-no-dup``.

    The ledger taps EVERY member's replicated store with the watcher's
    own compiled filter (member-agnostic by construction) and records,
    first-writer-wins, the (action, object id) each store version
    resolves to — convergent stores must agree, so a disagreement is
    itself a violation.  At scenario end each watcher's consumed payload
    stream is judged against the ledger: within each resync segment the
    consumed versions must be exactly the matching committed versions in
    order — no duplicate, no gap, no uncommitted interloper — however
    many member hops the stream survived.
    """

    def __init__(self, violations: Violations, pred, managers, tag: str):
        self.v = violations
        self.pred = pred
        self.managers = managers
        self.tag = tag
        self.ref: Dict[int, Tuple[str, str]] = {}
        self._subs: Dict[str, tuple] = {}   # member id -> (store, sub)

    def ensure(self) -> None:
        """(Re)subscribe to every member store; a crash-rebuilt store
        gets a fresh tap (its replayed prefix was already recorded live
        from the surviving members)."""
        for m in self.managers:
            if m.store is None:
                continue
            entry = self._subs.get(m.id)
            if entry is not None and entry[0] is m.store:
                continue
            sub = m.store.queue.subscribe(accepts_blocks=True)
            self._subs[m.id] = (m.store, sub)

    def drain(self) -> None:
        from ..state.events import Event, EventTaskBlock
        for mid, (_store, sub) in self._subs.items():
            while True:
                ev = sub.poll()
                if ev is None:
                    break
                if isinstance(ev, EventTaskBlock):
                    for e in ev.expand_events():
                        self._observe(mid, e)
                elif isinstance(ev, Event):
                    self._observe(mid, ev)

    def _observe(self, mid: str, ev) -> None:
        from ..state.events import event_version
        if not self.pred(ev):
            return
        ver = event_version(ev)
        key = (ev.action, ev.obj.id)
        seen = self.ref.get(ver)
        if seen is None:
            self.ref[ver] = key
        elif seen != key:
            self.v.record(
                "watch-resume-no-gap-no-dup",
                f"{self.tag}: members disagree on version {ver}: "
                f"{seen} vs {key} (from {mid}) — resume tokens are "
                "not member-portable")

    def judge(self, watcher) -> None:
        """Scenario end (all faults healed, watcher fully drained):
        validate every consumed segment against the ledger."""
        self.drain()
        ref_versions = sorted(self.ref)
        for seg in watcher.segments:
            start = seg["start"]
            consumed = seg["events"]
            last = consumed[-1][0] if consumed else start
            expected = [v for v in ref_versions if start < v <= last]
            got = [c[0] for c in consumed]
            if got != expected:
                gaps = sorted(set(expected) - set(got))[:5]
                dups = sorted({v for v in got
                               if got.count(v) > 1} | (set(got)
                              - set(expected)))[:5]
                self.v.record(
                    "watch-resume-no-gap-no-dup",
                    f"{watcher.name}: segment from v{start} diverged "
                    f"from the committed stream (missing {gaps}, "
                    f"extra/dup {dups}) across {watcher.hops} member "
                    "hop(s)")
                continue
            for ver, action, oid in consumed:
                if self.ref.get(ver) != (action, oid):
                    self.v.record(
                        "watch-resume-no-gap-no-dup",
                        f"{watcher.name}: payload at v{ver} is "
                        f"({action}, {oid}) but the cluster committed "
                        f"{self.ref.get(ver)}")
        # liveness: after heal+grace the stream must have caught up
        if ref_versions and watcher.segments:
            tail = watcher.segments[-1]
            last = tail["events"][-1][0] if tail["events"] \
                else tail["start"]
            behind = [v for v in ref_versions if v > last]
            if behind:
                self.v.record(
                    "watch-resume-no-gap-no-dup",
                    f"{watcher.name}: stream ended {len(behind)} "
                    f"committed event(s) behind the cluster "
                    f"(first missing v{behind[0]})")


def check_placement_quality(violations: Violations, store,
                            bound: float = 3.0,
                            record: str = "placement-quality") -> None:
    """Post-convergence placement-quality bound: with every fault healed,
    no live node may hold more than ``bound`` times the ideal even share
    of the RUNNING tasks (quality, not just safety — a converged-but-
    pathological packing is a scheduler regression chaos must catch)."""
    tasks = [t for t in store.view(lambda tx: tx.find(Task))
             if t.node_id
             and t.desired_state == TaskState.RUNNING
             and TaskState(t.status.state) == TaskState.RUNNING]
    nodes = [n for n in store.view(lambda tx: tx.find(Node))
             if n.status.state != NodeState.DOWN]
    if not tasks or not nodes or len(tasks) < len(nodes):
        return   # too sparse for a spread claim
    per_node: Dict[str, int] = {}
    for t in tasks:
        per_node[t.node_id] = per_node.get(t.node_id, 0) + 1
    ideal = len(tasks) / len(nodes)
    worst = max(per_node.items(), key=lambda kv: (kv[1], kv[0]))
    if worst[1] > bound * ideal:
        violations.record(
            record,
            f"node {worst[0]} runs {worst[1]} of {len(tasks)} tasks "
            f"(ideal {ideal:.1f}/node across {len(nodes)} live nodes, "
            f"bound {bound:.1f}x)")
