"""Safety invariants checked continuously during simulation.

Raft layer (checked on every state change, cluster-wide):

* single-leader-per-term — two members must never both be LEADER in the
  same term
* committed-entry agreement / no loss — once ANY member applies entry
  (index, term, digest), every member that ever applies that index must
  apply the identical entry, including after crash/restore from WAL

Control-plane layer (checked against the leader store's event stream):

* task FSM never moves backwards — observed status.state is monotone
  per task; desired_state is monotone per task
* terminal states are sticky — a COMPLETE/FAILED/... task never leaves
  the terminal set
* assignment liveness — when a task reaches ASSIGNED, its node exists
  and is not DOWN in the same store view
* no double assignment — a task's node_id never changes once set
* blocks are never failures — EventTaskBlock only ever carries
  assignment-band states (<= RUNNING), by contract
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Tuple

from ..models.objects import Node, Task
from ..models.types import NodeState, TaskState, TERMINAL_STATES
from ..state.events import Event, EventTaskBlock


class InvariantViolation(AssertionError):
    pass


class Violations:
    """Shared sink: checkers record, the runner decides pass/fail."""

    def __init__(self, engine):
        self.engine = engine
        self.items: List[str] = []

    def record(self, name: str, msg: str) -> None:
        line = f"INVARIANT {name}: {msg}"
        self.engine.log(line)
        self.items.append(f"t={self.engine.clock.elapsed():.3f} {line}")
        # mark the black box too: the post-mortem dump shows the
        # violation in context (surrounding spans/events), not alone
        from ..obs.flightrec import flightrec
        flightrec.note(line)


def entry_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


class RaftInvariants:
    def __init__(self, violations: Violations):
        self.v = violations
        self.leaders: Dict[int, str] = {}         # term -> leader id
        self.ledger: Dict[int, Tuple[int, str]] = {}  # index -> (term, digest)

    def observe_leader(self, term: int, member_id: str) -> None:
        seen = self.leaders.get(term)
        if seen is None:
            self.leaders[term] = member_id
        elif seen != member_id:
            # an election needs a majority of votes in that term; two
            # distinct winners for one term is a safety violation no
            # matter when each was observed
            self.v.record("single-leader-per-term",
                          f"term {term}: {seen} and {member_id} "
                          "are both leader")

    def observe_apply(self, member_id: str, index: int, term: int,
                      digest: str) -> None:
        seen = self.ledger.get(index)
        if seen is None:
            self.ledger[index] = (term, digest)
        elif seen != (term, digest):
            self.v.record(
                "no-committed-entry-loss",
                f"{member_id} applied ({term},{digest}) at index {index} "
                f"but the cluster committed {seen} there")

    def max_committed(self) -> int:
        return max(self.ledger) if self.ledger else 0


class TaskInvariants:
    """Subscribes to a store's event queue; ``drain()`` must be called
    after every synchronous control-plane step (single-threaded sim, so
    no events are ever in flight between checks)."""

    def __init__(self, violations: Violations, store):
        self.v = violations
        self.store = store
        self.states: Dict[str, int] = {}
        self.desired: Dict[str, int] = {}
        self.node_of: Dict[str, str] = {}
        # node states tracked from the SAME ordered event stream the
        # task observations come from: the assigned-node-live check must
        # compare an assignment against the node state committed BEFORE
        # it, not against the store's current row — drain can run behind
        # the commits (follower catch-up, deferred applies), where a
        # later DOWN would falsely indict an earlier valid assignment
        self.node_states: Dict[str, int] = {}
        self.sub = store.queue.subscribe(
            lambda ev: isinstance(ev, (Event, EventTaskBlock)),
            accepts_blocks=True)

    def drain(self) -> None:
        while True:
            ev = self.sub.poll()
            if ev is None:
                return
            if isinstance(ev, EventTaskBlock):
                self._check_block(ev)
                # observe the block's OWN payload (state/node arrays),
                # never the store's current row: drain may run behind a
                # catch-up burst (a rejoined member replaying a long
                # committed suffix), where the store is already ahead of
                # the event being drained — reading "current" there
                # manufactures false FSM regressions
                state = int(ev.state)
                for nid, items in ev.per_node().items():
                    for old, _ver in items:
                        self._observe(old.id, state,
                                      int(old.desired_state), nid)
                continue
            if isinstance(ev.obj, Node):
                if ev.action == "delete":
                    self.node_states.pop(ev.obj.id, None)
                else:
                    self.node_states[ev.obj.id] = \
                        int(ev.obj.status.state)
                continue
            if isinstance(ev.obj, Task) and ev.action != "delete":
                t = ev.obj
                self._observe(t.id, int(t.status.state),
                              int(t.desired_state), t.node_id)

    def _check_block(self, ev: EventTaskBlock) -> None:
        if ev.state > int(TaskState.RUNNING):
            self.v.record(
                "blocks-never-failures",
                f"task block committed state {ev.state} "
                f"(> RUNNING): blocks must only carry assignment states")

    def _observe(self, task_id: str, state: int, des: int,
                 node_id: str) -> None:
        """One observed (state, desired, node) triple for a task, from
        the event payload itself (per-task Event or block column)."""
        prev = self.states.get(task_id)
        if prev is not None:
            if state < prev:
                self.v.record(
                    "fsm-monotonic",
                    f"task {task_id[:8]} moved {TaskState(prev).name} -> "
                    f"{TaskState(state).name}")
            if TaskState(prev) in TERMINAL_STATES and state != prev \
                    and TaskState(state) not in TERMINAL_STATES:
                self.v.record(
                    "terminal-sticky",
                    f"task {task_id[:8]} left terminal "
                    f"{TaskState(prev).name} for {TaskState(state).name}")
        self.states[task_id] = state

        prev_des = self.desired.get(task_id)
        if prev_des is not None and des < prev_des:
            self.v.record(
                "desired-monotonic",
                f"task {task_id[:8]} desired moved "
                f"{TaskState(prev_des).name} -> {TaskState(des).name}")
        self.desired[task_id] = des

        if node_id:
            prev_node = self.node_of.get(task_id)
            if prev_node is not None and prev_node != node_id:
                self.v.record(
                    "no-double-assign",
                    f"task {task_id[:8]} reassigned {prev_node[:8]} -> "
                    f"{node_id[:8]} while live")
            self.node_of[task_id] = node_id

        if state == int(TaskState.ASSIGNED) and prev != state:
            ns = self.node_states.get(node_id) if node_id else None
            if ns is not None:
                # ordered knowledge: the node's last state committed
                # BEFORE this assignment — a DOWN here means the
                # scheduler placed onto a node it knew was dead
                if ns == int(NodeState.DOWN):
                    self.v.record(
                        "assigned-node-live",
                        f"task {task_id[:8]} ASSIGNED to DOWN node "
                        f"{node_id[:8]}")
            else:
                # no ordered knowledge (subscribed mid-stream): at least
                # the node must exist
                node = self.store.raw_get(Node, node_id) \
                    if node_id else None
                if node is None:
                    self.v.record(
                        "assigned-node-live",
                        f"task {task_id[:8]} ASSIGNED to missing node "
                        f"{node_id[:8] if node_id else '<none>'}")
